"""paddle_tpu.nn (reference surface: python/paddle/nn/)."""

from .layer import Layer, ParamAttr  # noqa: F401
from . import initializer  # noqa: F401
from . import functional  # noqa: F401
from .container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .common import (  # noqa: F401
    AlphaDropout,
    Bilinear,
    CosineSimilarity,
    Dropout,
    Dropout2D,
    Dropout3D,
    Embedding,
    Flatten,
    Identity,
    Linear,
    Pad1D,
    Pad2D,
    Pad3D,
    PixelShuffle,
    Unfold,
    Upsample,
    UpsamplingBilinear2D,
    UpsamplingNearest2D,
)
from .conv import Conv1D, Conv2D, Conv2DTranspose, Conv3D  # noqa: F401
from .norm import (  # noqa: F401
    BatchNorm,
    BatchNorm1D,
    BatchNorm2D,
    BatchNorm3D,
    GroupNorm,
    InstanceNorm1D,
    InstanceNorm2D,
    LayerNorm,
    LocalResponseNorm,
    RMSNorm,
    SpectralNorm,
    SyncBatchNorm,
)
from .pooling import (  # noqa: F401
    AdaptiveAvgPool1D,
    AdaptiveAvgPool2D,
    AdaptiveMaxPool2D,
    AvgPool1D,
    AvgPool2D,
    MaxPool1D,
    MaxPool2D,
)
from .activation import (  # noqa: F401
    CELU,
    ELU,
    GELU,
    Hardshrink,
    Hardsigmoid,
    Hardswish,
    Hardtanh,
    LeakyReLU,
    LogSoftmax,
    Maxout,
    Mish,
    PReLU,
    ReLU,
    ReLU6,
    SELU,
    Sigmoid,
    Silu,
    Softmax,
    Softplus,
    Softshrink,
    Softsign,
    Swish,
    Tanh,
    Tanhshrink,
    ThresholdedReLU,
)
from .loss import (  # noqa: F401
    BCELoss,
    BCEWithLogitsLoss,
    CrossEntropyLoss,
    HingeEmbeddingLoss,
    KLDivLoss,
    L1Loss,
    MarginRankingLoss,
    MSELoss,
    NLLLoss,
    SmoothL1Loss,
    TripletMarginLoss,
)
from .transformer import (  # noqa: F401
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)
from .rnn import (  # noqa: F401
    GRU,
    GRUCell,
    LSTM,
    LSTMCell,
    RNN,
    SimpleRNN,
    SimpleRNNCell,
)
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
    clip_grad_norm_,
    clip_grad_value_,
)
