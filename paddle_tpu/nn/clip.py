"""Gradient clipping (reference: python/paddle/nn/clip.py)."""

from __future__ import annotations

import jax.numpy as jnp

from .. import ops
from ..ops.dispatch import apply, coerce
from ..tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, ops.clip(g, self.min, self.max)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            cn = self.clip_norm
            clipped = apply(
                lambda a: a * jnp.minimum(1.0, cn / jnp.maximum(jnp.sqrt(jnp.sum(a * a)), 1e-12)),
                [coerce(g)],
                name="clip_by_norm",
            )
            out.append((p, clipped))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        grads = [g for p, g in params_grads if g is not None and getattr(p, "need_clip", True)]
        if not grads:
            return params_grads
        cn = self.clip_norm

        ins = [coerce(g) for g in grads]
        gnorm = apply(
            lambda *gs: jnp.sqrt(
                sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in gs)
            ),
            ins,
            name="global_norm",
        )
        scale = apply(
            lambda n: jnp.minimum(1.0, cn / jnp.maximum(n, 1e-12)), [gnorm], name="clip_scale"
        )
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, apply(lambda a, s: a * s.astype(a.dtype), [coerce(g), scale], name="clip_apply")))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    pgs = [(p, p.grad) for p in parameters if p.grad is not None]
    clip = ClipGradByGlobalNorm(max_norm)
    for p, g in clip(pgs):
        p.grad = g
    total = apply(
        lambda *gs: jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in gs)),
        [coerce(g) for _, g in pgs],
    )
    return total


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad = ops.clip(p.grad, -clip_value, clip_value)
