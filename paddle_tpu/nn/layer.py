"""nn.Layer — the module system (reference: python/paddle/nn/layer/layers.py).

Same lifecycle contract as the reference Layer (sublayers/parameters/buffers
registries, hooks, state_dict, train/eval), re-hosted on the XLA tensor.
"""

from __future__ import annotations

import collections

import numpy as np

from ..framework import core as _core
from ..tensor import Parameter, Tensor
from . import initializer as I


class ParamAttr:
    """paddle.ParamAttr — parameter configuration."""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        do_model_average=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"Invalid param attr {attr!r}")


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks, self._key = hooks, key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- attribute routing ------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                del params[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)

    # -- construction helpers --------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype or "float32"
        init = attr.initializer or default_initializer
        if init is None:
            if is_bias:
                init = I.Constant(0.0)
            else:
                init = I.XavierNormal() if I._global_weight_init is None else I._global_weight_init
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, persistable=False, dtype=None):
        import jax.numpy as jnp

        t = Tensor(jnp.zeros([], _core.to_jax_dtype(dtype or "float32")))
        t.persistable = persistable
        return t

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- traversal --------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield ((prefix + "." + name) if prefix else name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = prefix + "." + lname if prefix else lname
                for n, p in layer.named_parameters(sub_prefix, True):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (prefix + "." + name if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = prefix + "." + lname if prefix else lname
                yield from layer.named_buffers(sub_prefix, True)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_children(self):
        for name, layer in self._sub_layers.items():
            if layer is not None:
                yield name, layer

    def children(self):
        return [l for _, l in self.named_children()]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = prefix + "." + name if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(sub_prefix, False)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def apply(self, fn):
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # -- modes ------------------------------------------------------------
    def train(self):
        self.training = True
        for layer in self.children():
            layer.train()
        return self

    def eval(self):
        self.training = False
        for layer in self.children():
            layer.eval()
        return self

    # -- hooks ------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call -------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    # -- state dict -------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self._parameters.items():
            if p is not None:
                dest[structured_name_prefix + name] = p
        for name, b in self._buffers.items():
            if b is not None and name not in self._non_persistable_buffer_names:
                dest[structured_name_prefix + name] = b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is not None:
                    layer.state_dict(dest, True, structured_name_prefix + lname + ".")
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                target = own[k]
                src = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                import jax.numpy as jnp

                if list(src.shape) != list(target.shape):
                    raise ValueError(
                        f"Shape mismatch for '{k}': got {list(src.shape)}, expected {list(target.shape)}"
                    )
                target._data = jnp.asarray(src).astype(target._data.dtype)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype / device movement -----------------------------------------
    def _transform(self, fn):
        for _, p in self.named_parameters():
            p._data = fn(p._data)
            if p._grad_raw is not None:
                p._grad_raw = fn(p._grad_raw)
        for _, b in self.named_buffers():
            b._data = fn(b._data)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        import jax
        import jax.numpy as jnp

        if dtype is not None:
            jdt = _core.to_jax_dtype(_core.convert_dtype(dtype))
            self._transform(
                lambda a: a.astype(jdt) if jnp.issubdtype(a.dtype, jnp.floating) else a
            )
            self._dtype = _core.convert_dtype(dtype)
        if device is not None:
            if isinstance(device, _core.Place):
                place = device
            else:
                dev = str(device).lower()
                kind, _, idx = dev.partition(":")
                place = _core.CPUPlace(int(idx or 0)) if kind == "cpu" else _core.TPUPlace(int(idx or 0))
            self._transform(lambda a: jax.device_put(a, place.jax_device()))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope
