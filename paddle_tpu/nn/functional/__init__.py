"""nn.functional (reference: python/paddle/nn/functional/) — XLA lowerings.

Convs/pools use lax.conv_general_dilated / lax.reduce_window (MXU-friendly,
NCHW accepted and handled natively by XLA layout assignment); norms are
written so XLA fuses them; attention routes to the Pallas flash kernel.
"""

from __future__ import annotations

import math
import numbers

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...framework import core as _core
from ...framework.random import default_generator
from ...tensor import Tensor
from ...ops.dispatch import apply, coerce, amp_cast_inputs
from ...ops import matmul as _matmul
from ...ops.manipulation import label_smooth  # noqa: F401  (F.label_smooth)

# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------


def _unary(fn, name):
    def op(x, *args, **kwargs):
        x = coerce(x)
        return apply(fn, [x], name=name)

    op.__name__ = name
    return op


relu = _unary(jax.nn.relu, "relu")
relu6 = _unary(jax.nn.relu6, "relu6")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
tanh = _unary(jnp.tanh, "tanh")
silu = _unary(jax.nn.silu, "silu")
swish = silu
mish = _unary(lambda a: a * jnp.tanh(jax.nn.softplus(a)), "mish")
tanhshrink = _unary(lambda a: a - jnp.tanh(a), "tanhshrink")
softsign = _unary(jax.nn.soft_sign, "softsign")
hardswish = _unary(jax.nn.hard_swish, "hardswish")
hardsigmoid = _unary(lambda a: jnp.clip(a / 6.0 + 0.5, 0.0, 1.0), "hardsigmoid")


def relu_(x):
    from ...ops.dispatch import inplace_rebind

    return inplace_rebind(x, relu(x))


def gelu(x, approximate=False, name=None):
    x = coerce(x)
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate), [x], name="gelu")


def leaky_relu(x, negative_slope=0.01, name=None):
    x = coerce(x)
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope), [x], name="leaky_relu")


def elu(x, alpha=1.0, name=None):
    x = coerce(x)
    return apply(lambda a: jax.nn.elu(a, alpha), [x], name="elu")


def celu(x, alpha=1.0, name=None):
    x = coerce(x)
    return apply(lambda a: jax.nn.celu(a, alpha), [x], name="celu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    x = coerce(x)
    return apply(
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), [x], name="selu"
    )


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = coerce(x), coerce(weight)

    def f(a, w):
        if w.size > 1:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, w * a)

    return apply(f, [x, weight], name="prelu")


def rrelu(x, lower=0.125, upper=0.333, training=False, name=None):
    x = coerce(x)
    if training:
        key = default_generator.next_key()
        return apply(
            lambda a: jnp.where(
                a >= 0, a, a * jax.random.uniform(key, a.shape, a.dtype, lower, upper)
            ),
            [x],
            name="rrelu",
        )
    mid = (lower + upper) / 2
    return apply(lambda a: jnp.where(a >= 0, a, a * mid), [x], name="rrelu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.clip(a, min, max), [x], name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    x = coerce(x)
    return apply(
        lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0).astype(a.dtype), [x]
    )


def softshrink(x, threshold=0.5, name=None):
    x = coerce(x)
    return apply(
        lambda a: jnp.where(
            a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)
        ).astype(a.dtype),
        [x],
    )


def softplus(x, beta=1.0, threshold=20.0, name=None):
    x = coerce(x)
    return apply(
        lambda a: jnp.where(a * beta > threshold, a, jax.nn.softplus(a * beta) / beta),
        [x],
        name="softplus",
    )


def maxout(x, groups, axis=1, name=None):
    x = coerce(x)

    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        newshape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1 :]
        return jnp.max(a.reshape(newshape), axis=ax + 1)

    return apply(f, [x], name="maxout")


def softmax(x, axis=-1, dtype=None, name=None):
    x = coerce(x)
    (x,) = amp_cast_inputs([x], "black")
    return apply(lambda a: jax.nn.softmax(a, axis=axis), [x], name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = coerce(x)
    (x,) = amp_cast_inputs([x], "black")
    return apply(lambda a: jax.nn.log_softmax(a, axis=axis), [x], name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    x = coerce(x)
    key = default_generator.next_key()

    def f(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y).at[...].set(
                jnp.where(
                    jnp.arange(y.shape[axis]).reshape(
                        [-1 if i == (axis % y.ndim) else 1 for i in range(y.ndim)]
                    )
                    == idx,
                    1.0,
                    0.0,
                ).astype(y.dtype)
            )
            return y_hard - lax.stop_gradient(y) + y
        return y

    return apply(f, [x], name="gumbel_softmax")


def glu(x, axis=-1, name=None):
    x = coerce(x)
    return apply(lambda a: jax.nn.glu(a, axis=axis), [x], name="glu")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = coerce(x)
    return apply(
        lambda a: a
        / jnp.maximum(
            jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p), epsilon
        ),
        [x],
        name="normalize",
    )


# ---------------------------------------------------------------------------
# linear / embedding
# ---------------------------------------------------------------------------


def linear(x, weight, bias=None, name=None):
    """paddle semantics: weight shape [in_features, out_features]."""
    x, weight = coerce(x), coerce(weight)
    ins = [x, weight]
    if bias is not None:
        ins.append(coerce(bias))
    ins = amp_cast_inputs(ins, "white")

    def f(a, w, *b):
        out = jnp.matmul(a, w)
        if b:
            out = out + b[0]
        return out

    return apply(f, ins, name="linear")


def embedding(x, weight, padding_idx=None, sparse=False, name=None, max_norm=None, norm_type=2.0, scale_grad_by_freq=False):
    x, weight = coerce(x), coerce(weight)

    def f(i, w):
        idx = i.astype(jnp.int32)
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), w.dtype), out)
        return out

    return apply(f, [x, weight], name="embedding")


def one_hot(x, num_classes, name=None):
    from ...ops.manipulation import one_hot as _oh

    return _oh(x, num_classes)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = coerce(label)
    n = label.shape[-1]
    if prior_dist is not None:
        prior_dist = coerce(prior_dist)
        return apply(
            lambda l, p: (1 - epsilon) * l + epsilon * p, [label, prior_dist]
        )
    return apply(lambda l: (1 - epsilon) * l + epsilon / n, [label], name="label_smooth")


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return tuple(int(v) for _ in range(n))


def _conv_padding(padding, nsp, strides, kernel, dilation):
    """Returns lax padding spec: 'SAME'/'VALID' or list of (lo, hi)."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)) and len(padding) and isinstance(padding[0], (list, tuple)):
        # [[0,0],[0,0],[h0,h1],[w0,w1]] paddle style or per-dim pairs
        pairs = [tuple(p) for p in padding]
        if len(pairs) == nsp:
            return pairs
        return pairs[-nsp:]
    p = _tuplize(padding, nsp)
    if len(p) == 2 * nsp:
        return [(p[2 * i], p[2 * i + 1]) for i in range(nsp)]
    return [(pi, pi) for pi in p]


def conv2d(
    x,
    weight,
    bias=None,
    stride=1,
    padding=0,
    dilation=1,
    groups=1,
    data_format="NCHW",
    name=None,
):
    x, weight = coerce(x), coerce(weight)
    ins = [x, weight]
    if bias is not None:
        ins.append(coerce(bias))
    ins = amp_cast_inputs(ins, "white")
    strides = _tuplize(stride, 2)
    dil = _tuplize(dilation, 2)
    pad = _conv_padding(padding, 2, strides, None, dil)
    dn = ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC")

    s2d = _space_to_depth_plan(x.shape, weight.shape, strides, pad, dil, groups, data_format)

    def f(a, w, *b):
        if s2d is not None:
            out = _space_to_depth_conv(a, w, s2d, data_format)
        else:
            if data_format == "NHWC":
                w = jnp.transpose(w, (2, 3, 1, 0))
            out = lax.conv_general_dilated(
                a,
                w,
                window_strides=strides,
                padding=pad,
                rhs_dilation=dil,
                dimension_numbers=dn,
                feature_group_count=groups,
            )
        if b:
            bias_arr = b[0]
            shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
            out = out + bias_arr.reshape(shape)
        return out

    return apply(f, ins, name="conv2d")


def _space_to_depth_plan(xshape, wshape, strides, pad, dil, groups, data_format):
    """Decide whether a low-channel strided conv (a ResNet-style stem) should
    be rewritten as space-to-depth + dense conv.

    A 7x7/s2 conv on C=3 uses 3/128 of the MXU's lanes; regrouping sxs input
    pixels into channels turns it into an equivalent (k/s)x(k/s)/s1 conv on
    s*s*C channels, which tiles the MXU far better.  Returns a plan dict or
    None.  (TPU-native move; the reference's cuDNN picks specialized stem
    kernels instead — paddle/phi/kernels/gpu conv via cudnnFind.)
    """
    if groups != 1 or dil != (1, 1) or isinstance(pad, str):
        return None
    sh, sw = strides
    if sh != sw or sh < 2:
        return None
    cin = wshape[1]
    kh, kw = wshape[2], wshape[3]
    if cin * sh * sw > 32 or max(kh, kw) <= sh:
        return None
    hdim, wdim = (2, 3) if data_format == "NCHW" else (1, 2)
    H, W = xshape[hdim], xshape[wdim]
    k2h = -(-kh // sh) * sh  # kernel padded up to a stride multiple
    k2w = -(-kw // sw) * sw
    plan = {"s": sh, "k2": (k2h, k2w), "cin": cin, "cout": wshape[0], "k": (kh, kw)}
    for dim_len, (pl, pr), k, k2, key in (
        (H, pad[0], kh, k2h, "ph"),
        (W, pad[1], kw, k2w, "pw"),
    ):
        n_win = (dim_len + pl + pr - k) // sh + 1
        found = None
        for extra in range(0, 2 * sh):
            L = dim_len + pl + pr + extra
            if L % sh == 0 and (L - k2) // sh + 1 == n_win:
                found = (pl, pr + extra)
                break
        if found is None:
            return None
        plan[key] = found
    return plan


def _space_to_depth_conv(a, w, plan, data_format):
    """Equivalent conv after space-to-depth regrouping (see plan above)."""
    s = plan["s"]
    kh, kw = plan["k"]
    k2h, k2w = plan["k2"]
    cin, cout = plan["cin"], plan["cout"]
    (plh, prh), (plw, prw) = plan["ph"], plan["pw"]
    if data_format == "NCHW":
        a = jnp.transpose(a, (0, 2, 3, 1))  # stem only: one-off relayout
    n, _, _, _ = a.shape
    a = jnp.pad(a, ((0, 0), (plh, prh), (plw, prw), (0, 0)))
    H2, W2 = a.shape[1] // s, a.shape[2] // s
    # [N, H2, s, W2, s, C] -> [N, H2, W2, s*s*C]  (dh, dw, c) channel order
    a = a.reshape(n, H2, s, W2, s, cin).transpose(0, 1, 3, 2, 4, 5).reshape(n, H2, W2, s * s * cin)
    # weight OIHW -> padded HWIO -> regrouped [k2h/s, k2w/s, s*s*C, O]
    w = jnp.transpose(w, (2, 3, 1, 0))  # HWIO
    w = jnp.pad(w, ((0, k2h - kh), (0, k2w - kw), (0, 0), (0, 0)))
    w = w.reshape(k2h // s, s, k2w // s, s, cin, cout)
    w = w.transpose(0, 2, 1, 3, 4, 5).reshape(k2h // s, k2w // s, s * s * cin, cout)
    out = lax.conv_general_dilated(
        a, w, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if data_format == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    x, weight = coerce(x), coerce(weight)
    ins = [x, weight]
    if bias is not None:
        ins.append(coerce(bias))
    ins = amp_cast_inputs(ins, "white")
    strides = _tuplize(stride, 1)
    dil = _tuplize(dilation, 1)
    pad = _conv_padding(padding, 1, strides, None, dil)
    dn = ("NCH", "OIH", "NCH") if data_format == "NCL" else ("NHC", "HIO", "NHC")

    def f(a, w, *b):
        out = lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad, rhs_dilation=dil,
            dimension_numbers=dn, feature_group_count=groups,
        )
        if b:
            shape = [1, -1, 1] if data_format == "NCL" else [1, 1, -1]
            out = out + b[0].reshape(shape)
        return out

    return apply(f, ins, name="conv1d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    x, weight = coerce(x), coerce(weight)
    ins = [x, weight]
    if bias is not None:
        ins.append(coerce(bias))
    ins = amp_cast_inputs(ins, "white")
    strides = _tuplize(stride, 3)
    dil = _tuplize(dilation, 3)
    pad = _conv_padding(padding, 3, strides, None, dil)
    dn = ("NCDHW", "OIDHW", "NCDHW")

    def f(a, w, *b):
        out = lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad, rhs_dilation=dil,
            dimension_numbers=dn, feature_group_count=groups,
        )
        if b:
            out = out + b[0].reshape([1, -1, 1, 1, 1])
        return out

    return apply(f, ins, name="conv3d")


def conv2d_transpose(
    x, weight, bias=None, stride=1, padding=0, output_padding=0,
    groups=1, dilation=1, data_format="NCHW", output_size=None, name=None,
):
    if output_size is not None:
        # resolve the stride>1 output-length ambiguity the way the
        # reference does: derive the implied output_padding
        strides2 = _tuplize(stride, 2)
        dil2 = _tuplize(dilation, 2)
        pad2 = _conv_padding(padding, 2, strides2, None, dil2)
        if isinstance(pad2, str):
            raise NotImplementedError(
                "conv2d_transpose output_size with string padding is unsupported"
            )
        osz = _tuplize(output_size, 2)
        kh, kw = int(weight.shape[2]), int(weight.shape[3])
        opad = []
        for i, (k, insz) in enumerate(zip((kh, kw), (int(x.shape[2]), int(x.shape[3])))):
            base = (insz - 1) * strides2[i] - pad2[i][0] - pad2[i][1] + dil2[i] * (k - 1) + 1
            extra = int(osz[i]) - base
            if not 0 <= extra < strides2[i]:
                raise ValueError(
                    f"requested output_size[{i}]={osz[i]} unreachable "
                    f"(valid range [{base}, {base + strides2[i]}))"
                )
            opad.append(extra)
        output_padding = tuple(opad)
    x, weight = coerce(x), coerce(weight)
    ins = [x, weight]
    if bias is not None:
        ins.append(coerce(bias))
    ins = amp_cast_inputs(ins, "white")
    strides = _tuplize(stride, 2)
    dil = _tuplize(dilation, 2)
    pad = _conv_padding(padding, 2, strides, None, dil)
    opad = _tuplize(output_padding, 2)

    def f(a, w, *b):
        # weight layout: [in_c, out_c/groups, kh, kw] (paddle transpose-conv)
        kh, kw = w.shape[2], w.shape[3]
        if isinstance(pad, str):
            padding_pairs = pad
        else:
            padding_pairs = [
                (dil[i] * (k - 1) - pad[i][0], dil[i] * (k - 1) - pad[i][1] + opad[i])
                for i, k in enumerate((kh, kw))
            ]
        if groups > 1:
            # split input channels into groups for grouped transpose conv
            # (each group's kernel is flipped/transposed in the loop)
            ic = a.shape[1]
            outs = []
            icg = ic // groups
            for g in range(groups):
                outs.append(
                    lax.conv_general_dilated(
                        a[:, g * icg : (g + 1) * icg],
                        jnp.transpose(jnp.flip(w[g * icg : (g + 1) * icg], (2, 3)), (1, 0, 2, 3)),
                        window_strides=(1, 1),
                        padding=padding_pairs,
                        lhs_dilation=strides,
                        rhs_dilation=dil,
                        dimension_numbers=("NCHW", "OIHW", "NCHW"),
                    )
                )
            out = jnp.concatenate(outs, axis=1)
        else:
            # IOHW → rotate 180° → [out_c, in_c, kh, kw]
            w2 = jnp.transpose(jnp.flip(w, (2, 3)), (1, 0, 2, 3))
            out = lax.conv_general_dilated(
                a, w2, window_strides=(1, 1), padding=padding_pairs,
                lhs_dilation=strides, rhs_dilation=dil,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
            )
        if b:
            out = out + b[0].reshape([1, -1, 1, 1])
        return out

    return apply(f, ins, name="conv2d_transpose")


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


def _pool2d_spec(kernel_size, stride, padding, nhwc):
    """Shared window/stride/padding construction for the 2D pools.

    Returns (k, s, pad_spec, dims, strides).  A 4-pair paddle-style padding
    list is given in the data layout's order, so the spatial pairs are at
    [2:4] for NCHW but [1:3] for NHWC."""
    k = _tuplize(kernel_size, 2)
    s = _tuplize(stride if stride is not None else kernel_size, 2)
    if (
        nhwc
        and isinstance(padding, (list, tuple))
        and len(padding) == 4
        and isinstance(padding[0], (list, tuple))
    ):
        padding = [padding[0], padding[3], padding[1], padding[2]]  # -> NCHW order
    pad = _conv_padding(padding, 2, s, k, (1, 1))
    if isinstance(pad, str):
        pad_spec = pad
    elif nhwc:
        pad_spec = [(0, 0)] + list(pad) + [(0, 0)]
    else:
        pad_spec = [(0, 0), (0, 0)] + list(pad)
    dims = (1,) + k + (1,) if nhwc else (1, 1) + k
    strides = (1,) + s + (1,) if nhwc else (1, 1) + s
    return k, s, pad_spec, dims, strides


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCHW", name=None):
    x = coerce(x)
    k, s, pad_spec, dims, strides = _pool2d_spec(kernel_size, stride, padding, data_format == "NHWC")

    def f(a):
        init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
        return lax.reduce_window(a, init, lax.max, dims, strides, pad_spec)

    out = apply(f, [x], name="max_pool2d")
    if return_mask:
        idx = apply(lambda a: jnp.zeros_like(a, jnp.int32), [out.detach()])
        return out, idx
    return out


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    x = coerce(x)
    k, s, pad_spec, dims, strides = _pool2d_spec(kernel_size, stride, padding, data_format == "NHWC")

    def f(a):
        summed = lax.reduce_window(a, 0.0, lax.add, dims, strides, pad_spec)
        if divisor_override:
            return summed / divisor_override
        if exclusive and not isinstance(pad_spec, str):
            ones = jnp.ones_like(a)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pad_spec)
            return summed / counts
        return summed / (k[0] * k[1])

    return apply(f, [x], name="avg_pool2d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    x = coerce(x)
    k = _tuplize(kernel_size, 1)
    s = _tuplize(stride if stride is not None else kernel_size, 1)
    pad = _conv_padding(padding, 1, s, k, (1,))
    pad_spec = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + list(pad)

    def f(a):
        return lax.reduce_window(a, -jnp.inf, lax.max, (1, 1) + k, (1, 1) + s, pad_spec)

    return apply(f, [x], name="max_pool1d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    x = coerce(x)
    k = _tuplize(kernel_size, 1)
    s = _tuplize(stride if stride is not None else kernel_size, 1)
    pad = _conv_padding(padding, 1, s, k, (1,))
    pad_spec = pad if isinstance(pad, str) else [(0, 0), (0, 0)] + list(pad)

    def f(a):
        summed = lax.reduce_window(a, 0.0, lax.add, (1, 1) + k, (1, 1) + s, pad_spec)
        return summed / k[0]

    return apply(f, [x], name="avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    x = coerce(x)
    out_hw = _tuplize(output_size, 2)
    # one implementation parameterized over the spatial axes
    h_ax, w_ax = (2, 3) if data_format == "NCHW" else (1, 2)

    def f(a):
        h, w = a.shape[h_ax], a.shape[w_ax]
        oh, ow = out_hw
        if h % oh == 0 and w % ow == 0:
            ns = list(a.shape)
            ns[h_ax : h_ax + 1] = [oh, h // oh]
            ns[w_ax + 1 : w_ax + 2] = [ow, w // ow]
            return a.reshape(ns).mean((h_ax + 1, w_ax + 2))

        def _sl(axis, lo, hi):
            idx = [slice(None)] * a.ndim
            idx[axis] = slice(lo, hi)
            return tuple(idx)

        # general: mean over variable windows
        rows = [a[_sl(h_ax, (i * h) // oh, max((i * h) // oh + 1, ((i + 1) * h + oh - 1) // oh))].mean(h_ax, keepdims=True) for i in range(oh)]
        a2 = jnp.concatenate(rows, h_ax)
        cols = [a2[_sl(w_ax, (j * w) // ow, max((j * w) // ow + 1, ((j + 1) * w + ow - 1) // ow))].mean(w_ax, keepdims=True) for j in range(ow)]
        return jnp.concatenate(cols, w_ax)

    return apply(f, [x], name="adaptive_avg_pool2d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    x = coerce(x)
    out_hw = _tuplize(output_size, 2)

    def f(a):
        n, c, h, w = a.shape
        oh, ow = out_hw
        if h % oh == 0 and w % ow == 0:
            return a.reshape(n, c, oh, h // oh, ow, w // ow).max((3, 5))
        rows = [a[:, :, (i * h) // oh : ((i + 1) * h + oh - 1) // oh, :].max(2, keepdims=True) for i in range(oh)]
        a2 = jnp.concatenate(rows, 2)
        cols = [a2[:, :, :, (j * w) // ow : ((j + 1) * w + ow - 1) // ow].max(3, keepdims=True) for j in range(ow)]
        return jnp.concatenate(cols, 3)

    return apply(f, [x], name="adaptive_max_pool2d")


def adaptive_avg_pool1d(x, output_size, name=None):
    x = coerce(x)
    o = int(output_size) if not isinstance(output_size, (list, tuple)) else int(output_size[0])

    def f(a):
        n, c, l = a.shape
        if l % o == 0:
            return a.reshape(n, c, o, l // o).mean(3)
        parts = [a[:, :, (i * l) // o : ((i + 1) * l + o - 1) // o].mean(2, keepdims=True) for i in range(o)]
        return jnp.concatenate(parts, 2)

    return apply(f, [x], name="adaptive_avg_pool1d")


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    x = coerce(x)
    (x,) = amp_cast_inputs([x], "black")
    if isinstance(normalized_shape, numbers.Integral):
        normalized_shape = (int(normalized_shape),)
    naxes = tuple(range(-len(tuple(normalized_shape)), 0))
    ins = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(amp_cast_inputs([coerce(weight)], "black")[0])
    if has_b:
        ins.append(amp_cast_inputs([coerce(bias)], "black")[0])

    def f(a, *wb):
        # stats in fp32, output in the activation dtype; weight/bias are cast
        # to the activation dtype so fp32 norm params never promote the
        # residual stream (the round-1 AMP-O2 OOM: bf16 * f32 -> f32 matmuls)
        dtype = a.dtype
        a32 = a.astype(jnp.float32)
        mean = jnp.mean(a32, axis=naxes, keepdims=True)
        var = jnp.var(a32, axis=naxes, keepdims=True)
        out = ((a32 - mean) * lax.rsqrt(var + epsilon)).astype(dtype)
        i = 0
        if has_w:
            out = out * wb[i].astype(dtype)
            i += 1
        if has_b:
            out = out + wb[i].astype(dtype)
        return out

    return apply(f, ins, name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """TPU-native extension (reference counterpart: fused_rms_norm in
    paddle/phi/kernels/fusion — standard in the Llama family)."""
    x = coerce(x)
    ins = [x]
    if weight is not None:
        ins.append(coerce(weight))

    def f(a, *w):
        dtype = a.dtype
        a32 = a.astype(jnp.float32)
        out = a32 * lax.rsqrt(jnp.mean(a32 * a32, axis=-1, keepdims=True) + epsilon)
        out = out.astype(dtype)
        if w:
            # cast fp32 norm weight down — bf16 * f32 would promote the whole
            # residual stream to f32 (round-1 AMP-O2 OOM)
            out = out * w[0].astype(dtype)
        return out

    return apply(f, ins, name="rms_norm")


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    x = coerce(x)
    # The activation stays in its AMP dtype (bf16 under O2): stats and the
    # per-channel scale/shift are computed in fp32 *inside* the kernel so XLA
    # fuses the casts into the elementwise op — HBM traffic stays bf16.
    # (Black-casting x here doubled activation bytes across the whole ResNet.)
    ch_axis = 1 if data_format.startswith("NC") and x.ndim > 1 else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis] if x.ndim > 1 else 1

    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        stats_ins = [x]
        has_shift = running_mean is not None
        if has_shift:
            stats_ins.append(coerce(running_mean))

        def _stats(a, *k_in):
            # one fused pass: shifted sum and sum-of-squares reduce together
            # (XLA multi-output fusion).  Shifting by the running mean (an
            # independent [C] input, so the broadcast-subtract fuses into the
            # reduce) keeps the single-pass E[(x-k)^2] - E[x-k]^2 form from
            # cancelling catastrophically when |mean| >> std once stats have
            # warmed up; shift-invariance makes the x-gradient exact either
            # way.  (A data-derived shift would be exact from step 0 but
            # forces XLA to materialize the shifted activations — measured
            # ~10% off ResNet50 step time.)
            #
            # Channels-last inputs reduce over a [rows, C] VIEW: XLA's
            # row-major column reduction is ~10x faster than the
            # multi-axis-keep-minor form on TPU (measured 80 -> 7 ms
            # standalone on [256,56,56,256]).
            if ch_axis == a.ndim - 1:
                a32 = a.reshape(-1, a.shape[-1]).astype(jnp.float32)
                red = (0,)
                kshape = (1, a.shape[-1])
            else:
                a32 = a.astype(jnp.float32)
                red = reduce_axes
                kshape = shape
            k = (
                jax.lax.stop_gradient(k_in[0].astype(jnp.float32)).reshape(kshape)
                if k_in
                else jnp.zeros(kshape, jnp.float32)
            )
            d = a32 - k
            m = jnp.mean(d, axis=red)
            ms = jnp.mean(d * d, axis=red)
            return m + k.reshape(m.shape), jnp.maximum(ms - m * m, 0.0)

        mean, var = apply(_stats, stats_ins, name="bn_stats", multi=True)
        # update running stats in-place (buffers)
        if running_mean is not None:
            from ... import ops as _ops

            with _core.no_grad_ctx():
                running_mean._data = (
                    momentum * running_mean._data + (1 - momentum) * mean._data
                )
                n = int(np.prod([x.shape[i] for i in reduce_axes]))
                unbiased = var._data * (n / max(n - 1, 1))
                running_var._data = (
                    momentum * running_var._data + (1 - momentum) * unbiased
                )
    else:
        mean = coerce(running_mean)
        var = coerce(running_var)

    ins = [x, mean, var]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(amp_cast_inputs([coerce(weight)], "black")[0])
    if has_b:
        ins.append(amp_cast_inputs([coerce(bias)], "black")[0])

    def f(a, m, v, *wb):
        dtype = a.dtype
        m32 = m.astype(jnp.float32)
        inv = lax.rsqrt(v.astype(jnp.float32) + epsilon)
        i = 0
        if has_w:
            inv = inv * wb[i].astype(jnp.float32)
            i += 1
        shift = -m32 * inv
        if has_b:
            shift = shift + wb[i].astype(jnp.float32)
        # one FMA per element; per-channel scale/shift precomputed on [C]
        out = a.astype(jnp.float32) * inv.reshape(shape) + shift.reshape(shape)
        return out.astype(dtype)

    return apply(f, ins, name="batch_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    x = coerce(x)
    ins = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(coerce(weight))
    if has_b:
        ins.append(coerce(bias))

    def f(a, *wb):
        dtype = a.dtype
        n, c = a.shape[0], a.shape[1]
        spatial = a.shape[2:]
        g = num_groups
        a2 = a.reshape((n, g, c // g) + spatial).astype(jnp.float32)
        axes = tuple(range(2, a2.ndim))
        mean = jnp.mean(a2, axis=axes, keepdims=True)
        var = jnp.var(a2, axis=axes, keepdims=True)
        out = ((a2 - mean) * lax.rsqrt(var + epsilon)).reshape(a.shape).astype(dtype)
        shape = [1, c] + [1] * len(spatial)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape).astype(dtype)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape).astype(dtype)
        return out

    return apply(f, ins, name="group_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    x = coerce(x)
    ins = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(coerce(weight))
    if has_b:
        ins.append(coerce(bias))

    def f(a, *wb):
        dtype = a.dtype
        a32 = a.astype(jnp.float32)
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a32, axis=axes, keepdims=True)
        var = jnp.var(a32, axis=axes, keepdims=True)
        out = ((a32 - mean) * lax.rsqrt(var + eps)).astype(dtype)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape).astype(dtype)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape).astype(dtype)
        return out

    return apply(f, ins, name="instance_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    x = coerce(x)

    def f(a):
        sq = jnp.square(a)
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[1] = (half, size - half - 1)
        sq_p = jnp.pad(sq, pads)
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + lax.slice_in_dim(sq_p, i, i + a.shape[1], axis=1)
        return a / (k + alpha * acc) ** beta

    return apply(f, [x], name="local_response_norm")


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = coerce(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda a: a * (1 - p), [x], name="dropout_infer")
        return x
    if p == 1.0:
        return apply(lambda a: jnp.zeros_like(a), [x], name="dropout")
    key = default_generator.next_key()

    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype)).astype(a.dtype)
        return jnp.where(keep, a, jnp.zeros((), a.dtype)).astype(a.dtype)

    return apply(f, [x], name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    return dropout(x, p, axis=[0, 1] if data_format == "NCHW" else [0, 3], training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    return dropout(x, p, axis=[0, 1], training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = coerce(x)
    if not training or p == 0.0:
        return x
    key = default_generator.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p**2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    return apply(f, [x], name="alpha_dropout")


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


def _reduce(v, reduction):
    from ... import ops as _ops

    if reduction == "mean":
        return _ops.mean(v)
    if reduction == "sum":
        return _ops.sum(v)
    return v


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    input, label = coerce(input), coerce(label)
    (input,) = amp_cast_inputs([input], "black")
    ins = [input, label]
    has_w = weight is not None
    if has_w:
        ins.append(coerce(weight))

    def f(logits, lab, *w):
        out_dtype = logits.dtype if jnp.issubdtype(logits.dtype, jnp.floating) else jnp.float32
        # fp32 math expressed so XLA fuses the upcast into the reductions —
        # never materialize a full fp32 [*, vocab] log-softmax (at vocab=32k
        # that's a 2GB HBM temp per buffer, the round-1 bench OOM tail)
        nclass = logits.shape[axis]
        logits32 = logits.astype(jnp.float32)
        if use_softmax:
            lse = jax.scipy.special.logsumexp(logits32, axis=axis, keepdims=True)
        else:
            lse = jnp.zeros_like(jnp.sum(logits32, axis=axis, keepdims=True))
            logits32 = jnp.log(jnp.maximum(logits32, 1e-30))
        if soft_label:
            tgt = lab.astype(jnp.float32)
            if label_smoothing > 0:
                tgt = (1 - label_smoothing) * tgt + label_smoothing / nclass
            # sum(tgt * (logits - lse)) fuses; tgt rows sum to 1
            loss = -(tgt * (logits32 - lse)).sum(axis=axis)
            valid = jnp.ones(loss.shape, jnp.float32)
        else:
            idx = lab.astype(jnp.int32)
            if idx.ndim == logits32.ndim and idx.shape[axis] == 1:
                idx = jnp.squeeze(idx, axis)
            valid = (idx != ignore_index).astype(jnp.float32)
            safe_idx = jnp.where(idx == ignore_index, 0, idx)
            picked = (
                jnp.take_along_axis(logits32, jnp.expand_dims(safe_idx, axis), axis=axis)
                - lse
            ).squeeze(axis)
            if label_smoothing > 0:
                smooth = -(jnp.mean(logits32, axis=axis, keepdims=True) - lse).squeeze(axis)
                loss = (1 - label_smoothing) * (-picked) + label_smoothing * smooth
            else:
                loss = -picked
            if use_softmax:
                # softmax CE is >= 0 exactly; XLA's fused bf16 rounding can
                # leave -ulp noise on fully-confident samples — clamp it
                loss = jnp.maximum(loss, 0.0)
            loss = loss * valid
            if w:
                cw = jnp.take(w[0], safe_idx, axis=0).astype(jnp.float32) * valid
                loss = loss * jnp.take(w[0], safe_idx, axis=0).astype(jnp.float32)
                if reduction == "mean":
                    return (loss.sum() / jnp.maximum(cw.sum(), 1e-12)).astype(out_dtype)
        if reduction == "mean":
            return (loss.sum() / jnp.maximum(valid.sum(), 1.0)).astype(out_dtype)
        if reduction == "sum":
            return loss.sum().astype(out_dtype)
        return loss.astype(out_dtype)

    return apply(f, ins, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    from ...ops.manipulation import unsqueeze

    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    input, label = coerce(input), coerce(label)
    ins = [input, label]
    has_w = weight is not None
    if has_w:
        ins.append(coerce(weight))

    def f(logp, lab, *w):
        idx = lab.astype(jnp.int32)
        valid = (idx != ignore_index).astype(logp.dtype)
        safe = jnp.where(idx == ignore_index, 0, idx)
        picked = jnp.take_along_axis(logp, safe[..., None] if logp.ndim == idx.ndim + 1 else safe, axis=1 if logp.ndim == 2 else 1)
        if logp.ndim == 2:
            picked = jnp.take_along_axis(logp, safe[:, None], axis=1)[:, 0]
        loss = -picked * valid
        if w:
            cw = jnp.take(w[0], safe, axis=0)
            loss = loss * cw
            if reduction == "mean":
                return loss.sum() / jnp.maximum((cw * valid).sum(), 1e-12)
        if reduction == "mean":
            return loss.sum() / jnp.maximum(valid.sum(), 1.0)
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply(f, ins, name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    input, label = coerce(input), coerce(label)

    def f(a, b):
        d = jnp.square(a - b.astype(a.dtype))
        if reduction == "mean":
            return d.mean()
        if reduction == "sum":
            return d.sum()
        return d

    return apply(f, [input, label], name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    input, label = coerce(input), coerce(label)

    def f(a, b):
        d = jnp.abs(a - b.astype(a.dtype))
        if reduction == "mean":
            return d.mean()
        if reduction == "sum":
            return d.sum()
        return d

    return apply(f, [input, label], name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    input, label = coerce(input), coerce(label)

    def f(a, b):
        d = a - b.astype(a.dtype)
        ad = jnp.abs(d)
        loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply(f, [input, label], name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    input, label = coerce(input), coerce(label)
    ins = [input, label] + ([coerce(weight)] if weight is not None else [])

    def f(p, y, *w):
        y = y.astype(p.dtype)
        eps = 1e-12
        loss = -(y * jnp.log(jnp.maximum(p, eps)) + (1 - y) * jnp.log(jnp.maximum(1 - p, eps)))
        if w:
            loss = loss * w[0]
        return _red(loss)

    def _red(loss):
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply(f, ins, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    logit, label = coerce(logit), coerce(label)
    ins = [logit, label]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        ins.append(coerce(weight))
    if has_pw:
        ins.append(coerce(pos_weight))

    def f(z, y, *rest):
        y = y.astype(z.dtype)
        i = 0
        w = None
        pw = None
        if has_w:
            w = rest[i]
            i += 1
        if has_pw:
            pw = rest[i]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight variant
        if pw is not None:
            log_weight = (pw - 1) * y + 1
            loss = (1 - y) * z + log_weight * (jnp.logaddexp(0.0, -jnp.abs(z)) + jnp.maximum(-z, 0.0))
        else:
            loss = jnp.maximum(z, 0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        if w is not None:
            loss = loss * w
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply(f, ins, name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    input, label = coerce(input), coerce(label)

    def f(lp, t):
        if log_target:
            loss = jnp.exp(t) * (t - lp)
        else:
            t = t.astype(lp.dtype)
            loss = t * (jnp.log(jnp.maximum(t, 1e-12)) - lp)
        if reduction == "mean":
            return loss.mean()
        if reduction == "batchmean":
            return loss.sum() / lp.shape[0]
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply(f, [input, label], name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    input, other, label = coerce(input), coerce(other), coerce(label)

    def f(a, b, y):
        loss = jnp.maximum(0.0, -y.astype(a.dtype) * (a - b) + margin)
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply(f, [input, other, label], name="margin_ranking_loss")


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    x1, x2 = coerce(x1), coerce(x2)

    def f(a, b):
        num = (a * b).sum(axis)
        den = jnp.sqrt(jnp.square(a).sum(axis)) * jnp.sqrt(jnp.square(b).sum(axis))
        return num / jnp.maximum(den, eps)

    return apply(f, [x1, x2], name="cosine_similarity")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    logit, label = coerce(logit), coerce(label)
    ins = [logit, label] + ([coerce(normalizer)] if normalizer is not None else [])

    def f(z, y, *n):
        y = y.astype(z.dtype)
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply(f, ins, name="sigmoid_focal_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    input, label = coerce(input), coerce(label)

    def f(a, y):
        y = y.astype(a.dtype)
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply(f, [input, label], name="hinge_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    input, positive, negative = coerce(input), coerce(positive), coerce(negative)

    def f(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v) ** p, axis=-1) ** (1.0 / p)

        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        loss = jnp.maximum(d_ap - d_an + margin, 0.0)
        if reduction == "mean":
            return loss.mean()
        if reduction == "sum":
            return loss.sum()
        return loss

    return apply(f, [input, positive, negative], name="triplet_margin_loss")


# ---------------------------------------------------------------------------
# attention (routes to pallas flash attention)
# ---------------------------------------------------------------------------


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True,
    name=None, *, segment_ids=None
):
    """Inputs [batch, seq, heads, head_dim] (paddle convention).
    segment_ids: optional [batch, seq] int packed-sequence/padding masking
    that keeps the Pallas kernel eligible (see ops/flash_attention.py)."""
    from ...ops.flash_attention import scaled_dot_product_attention as _sdpa

    return _sdpa(query, key, value, attn_mask, dropout_p, is_causal, training, segment_ids)


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False, name=None):
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal)
    if return_softmax:
        return out, None
    return out, None


def flash_decode(query, key, value, pos, scale=None):
    """Cached static-KV attention: q [b, sq, h, d] against full cache
    buffers k/v [b, L, kv_h, d]; `pos` (scalar int32 Tensor) is the write
    position — validity is computed in-kernel from it, so the decode path
    stays Pallas-eligible (no additive mask)."""
    from ...ops.flash_attention import flash_decode as _fd

    return _fd(query, key, value, pos, scale)


def paged_flash_decode(query, arena_k, arena_v, tables, pos, max_len, scale=None,
                       kernel="auto", k_scale=None, v_scale=None):
    """Cached attention over a block-paged KV pool: q [b, sq, h, d] against
    per-layer arenas [num_pages, page_size, kv_h, d], addressed through
    `tables` ([b, max_pages_per_seq] int32, traced data).  The page
    indirection happens inside the compiled step; validity comes from `pos`
    exactly as in flash_decode, so paged and dense decode are bit-identical.
    `kernel` selects the dispatch: "auto" (fused Pallas arena-reading kernel
    when eligible, else gather-then-dense), "fused", or "gather".  When the
    arenas are int8-quantized, pass their per-row scale arenas as
    `k_scale`/`v_scale` ([num_pages, page_size, kv_h, 1] float32) — both
    dispatches then dequantize through the same page tables."""
    from ...ops.flash_attention import paged_flash_decode as _pfd

    return _pfd(query, arena_k, arena_v, tables, pos, max_len, scale,
                kernel=kernel, k_scale=k_scale, v_scale=v_scale)


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = coerce(x)
    k = _tuplize(kernel_sizes, 2)
    s = _tuplize(strides, 2)
    d = _tuplize(dilations, 2)
    p = _tuplize(paddings, 2)

    def f(a):
        n, c, h, w = a.shape
        a_p = jnp.pad(a, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        oh = (h + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (w + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        cols = []
        for i in range(k[0]):
            for j in range(k[1]):
                patch = a_p[:, :, i * d[0] : i * d[0] + oh * s[0] : s[0], j * d[1] : j * d[1] + ow * s[1] : s[1]]
                cols.append(patch.reshape(n, c, -1))
        return jnp.stack(cols, 2).reshape(n, c * k[0] * k[1], -1)

    return apply(f, [x], name="unfold")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    x = coerce(x)

    def f(a):
        n, c, h, w = a.shape
        if size is not None:
            oh, ow = _tuplize(size, 2)
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else (scale_factor, scale_factor)
            oh, ow = int(h * sf[0]), int(w * sf[1])
        method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
        a2 = jnp.moveaxis(a, 1, -1)
        out = jax.image.resize(a2, (n, oh, ow, c), method=method)
        return jnp.moveaxis(out, -1, 1)

    return apply(f, [x], name="interpolate")


upsample = interpolate


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = coerce(x)
    r = upscale_factor

    def f(a):
        n, c, h, w = a.shape
        a2 = a.reshape(n, c // (r * r), r, r, h, w)
        a2 = jnp.transpose(a2, (0, 1, 4, 2, 5, 3))
        return a2.reshape(n, c // (r * r), h * r, w * r)

    return apply(f, [x], name="pixel_shuffle")


def pad(x, pad_width, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...ops.manipulation import pad as _pad

    return _pad(x, pad_width, mode, value, data_format)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    x = coerce(x)

    def f(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        a2 = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([a2[:, 1:, :fold], jnp.zeros_like(a2[:, :1, :fold])], 1)
        right = jnp.concatenate([jnp.zeros_like(a2[:, :1, fold : 2 * fold]), a2[:, :-1, fold : 2 * fold]], 1)
        rest = a2[:, :, 2 * fold :]
        return jnp.concatenate([left, right, rest], 2).reshape(nt, c, h, w)

    return apply(f, [x], name="temporal_shift")


def linear_fp8(x, weight, bias=None, name=None):
    """Linear through the fp8 (e4m3) quantization grid with per-tensor
    scaling — see paddle_tpu.incubate.fp8 (reference: incubate fp8)."""
    from ...incubate.fp8 import linear_fp8 as _impl

    return _impl(x, weight, bias)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    """x if x > threshold else value (reference: F.thresholded_relu)."""
    x = coerce(x)
    return apply(
        lambda a: jnp.where(a > threshold, a, jnp.asarray(value, a.dtype)),
        [x],
        name="thresholded_relu",
    )


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    """[..., L] mask with mask[..., j] = j < lengths[...] (reference:
    paddle.nn.functional.sequence_mask).  maxlen must be static (XLA
    shapes); defaults to int(max(lengths)) computed eagerly."""
    lengths = coerce(lengths)
    if maxlen is None:
        if isinstance(lengths._data, jax.core.Tracer):
            raise ValueError(
                "sequence_mask needs an explicit maxlen inside traced code "
                "(output shape must be static for XLA)"
            )
        maxlen = int(jnp.max(lengths._raw))
    jd = _core.to_jax_dtype(dtype)

    def f(l):
        pos = jnp.arange(maxlen)
        return (pos[None, :] < l.reshape(-1, 1)).reshape(l.shape + (maxlen,)).astype(jd)

    return apply(f, [lengths], name="sequence_mask")


def conv1d_transpose(
    x, weight, bias=None, stride=1, padding=0, output_padding=0,
    groups=1, dilation=1, data_format="NCL", output_size=None, name=None,
):
    """1-D transpose conv via the 2-D kernel on a unit spatial dim
    (reference: F.conv1d_transpose)."""
    from ... import ops as _ops

    if output_size is not None:
        raise NotImplementedError(
            "conv1d_transpose output_size is not supported; use "
            "output_padding to resolve the stride ambiguity"
        )
    if data_format != "NCL":
        raise NotImplementedError("conv1d_transpose supports NCL layout only")
    x = coerce(x)
    weight = coerce(weight)
    def lift(v, kind):
        """1-D arg -> 2-D with a unit leading spatial dim (stride/dilation
        lead with 1, paddings with 0)."""
        lead = {"stride": 1, "dil": 1, "pad": 0, "opad": 0}[kind]
        if isinstance(v, str):
            # lax.conv_general_dilated rejects string padding for transposed
            # convs; surface that up-front instead of deep in lax
            raise NotImplementedError(
                "conv1d_transpose does not support string padding; pass "
                "explicit int/[lo, hi] padding"
            )
        if isinstance(v, (list, tuple)):
            if len(v) == 1:
                return (lead, int(v[0]))
            if kind == "pad":
                if all(isinstance(e, (list, tuple)) and len(e) == 2 for e in v):
                    # reference pair forms: [[lo,hi]] or [[0,0],[0,0],[lo,hi]]
                    lo, hi = v[-1]
                    return [[0, 0], [int(lo), int(hi)]]
                if len(v) == 2:
                    # asymmetric [lo, hi] on L -> [[0, 0], [lo, hi]]
                    return [[0, 0], [int(v[0]), int(v[1])]]
            raise ValueError(f"conv1d_transpose {kind}={v!r} not understood")
        return (lead, int(v))

    x4 = _ops.unsqueeze(x, 2)  # [N, C, 1, L]
    w4 = _ops.unsqueeze(weight, 2)  # [in, out/g, 1, K]
    out = conv2d_transpose(
        x4, w4, bias=bias,
        stride=lift(stride, "stride"),
        padding=lift(padding, "pad"),
        output_padding=lift(output_padding, "opad"),
        groups=groups,
        dilation=lift(dilation, "dil"),
    )
    return _ops.squeeze(out, 2)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """[N, 2, 3] affine matrices -> [N, H, W, 2] sampling grid in [-1, 1]
    coords (reference: F.affine_grid)."""
    theta = coerce(theta)
    n, c, h, w = [int(s) for s in out_shape]

    def f(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1.0
            xs = (jnp.arange(w) * 2 + 1) / w - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
        return jnp.einsum("hwk,njk->nhwj", base.astype(th.dtype), th)

    return apply(f, [theta], name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    """Bilinear/nearest sampling of x [N,C,H,W] at grid [N,Ho,Wo,2] (x,y in
    [-1,1]) — reference: F.grid_sample."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be bilinear/nearest, got {mode}")
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError("grid_sample padding_mode: zeros/border only")
    x, grid = coerce(x), coerce(grid)

    def f(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def fetch(ix, iy):
            # gather with border clamp; zeros handled by validity mask
            cx = jnp.clip(ix, 0, w - 1)
            cy = jnp.clip(iy, 0, h - 1)
            vals = a[jnp.arange(n)[:, None, None], :, cy, cx]  # [N,Ho,Wo,C]
            if padding_mode == "zeros":
                ok = (ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1)
                vals = vals * ok[..., None].astype(vals.dtype)
            return vals

        if mode == "nearest":
            # half-away-from-zero like the reference kernel's ::round (jnp
            # rounds half to even, and floor(t+0.5) is half-UP, which picks
            # pixel 0 instead of -1 at negative half positions)
            rnd = lambda t: jnp.where(
                t >= 0, jnp.floor(t + 0.5), jnp.ceil(t - 0.5)
            ).astype(jnp.int32)
            out = fetch(rnd(fx), rnd(fy))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            wx = (fx - x0)[..., None]
            wy = (fy - y0)[..., None]
            out = (
                fetch(x0, y0) * (1 - wx) * (1 - wy)
                + fetch(x0 + 1, y0) * wx * (1 - wy)
                + fetch(x0, y0 + 1) * (1 - wx) * wy
                + fetch(x0 + 1, y0 + 1) * wx * wy
            )
        return jnp.transpose(out, (0, 3, 1, 2))  # [N,C,Ho,Wo]

    return apply(f, [x, grid], name="grid_sample")


# ---------------------------------------------------------------------------
# round-4 API-breadth pass (§2.3 long tail): losses, 3D pools, fold, CTC
# ---------------------------------------------------------------------------


log_sigmoid = _unary(jax.nn.log_sigmoid, "log_sigmoid")


def square_error_cost(input, label):
    input, label = coerce(input), coerce(label)
    return apply(lambda a, b: (a - b) ** 2, [input, label], name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    input, label = coerce(input), coerce(label)
    return apply(
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        [input, label],
        name="log_loss",
    )


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    input, label = coerce(input), coerce(label)

    def f(a, b):
        d = a - b
        ad = jnp.abs(d)
        out = jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
        if reduction == "mean":
            return out.mean()
        if reduction == "sum":
            return out.sum()
        return out

    return apply(f, [input, label], name="huber_loss")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    x, y = coerce(x), coerce(y)
    return apply(
        lambda a, b: jnp.sum(jnp.abs(a - b + epsilon) ** p, axis=-1, keepdims=keepdim)
        ** (1.0 / p),
        [x, y],
        name="pairwise_distance",
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    input1, input2, label = coerce(input1), coerce(input2), coerce(label)

    def f(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        out = jnp.where(y > 0, 1 - cos, jnp.maximum(0.0, cos - margin))
        if reduction == "mean":
            return out.mean()
        if reduction == "sum":
            return out.sum()
        return out

    return apply(f, [input1, input2, label], name="cosine_embedding_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    """input [N, ..., C] probabilities, label [N, ..., 1] int (reference
    semantics: one-hot overlap over all but the batch dim)."""
    input, label = coerce(input), coerce(label)

    def f(p, y):
        c = p.shape[-1]
        oh = jax.nn.one_hot(y[..., 0].astype(jnp.int32), c, dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * oh, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(oh, axis=reduce_dims)
        return (1 - (2 * inter + epsilon) / (union + epsilon)).mean()

    return apply(f, [input, label], name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    anchor, positive, labels = coerce(anchor), coerce(positive), coerce(labels)

    def f(a, p, y):
        reg = l2_reg * (jnp.sum(a * a, -1).mean() + jnp.sum(p * p, -1).mean()) / 4
        sim = a @ p.T  # [B, B]
        same = (y[:, None] == y[None, :]).astype(jnp.float32)
        tgt = same / jnp.maximum(same.sum(-1, keepdims=True), 1)
        ce = -(tgt * jax.nn.log_softmax(sim, -1)).sum(-1).mean()
        return ce + reg

    return apply(f, [anchor, positive, labels], name="npair_loss")


def bilinear(x1, x2, weight, bias=None, name=None):
    """out[.., o] = x1 W_o x2 (+b); weight [out, in1, in2]."""
    x1, x2, weight = coerce(x1), coerce(x2), coerce(weight)
    ins = [x1, x2, weight]
    if bias is not None:
        ins.append(coerce(bias))

    def f(a, b, w, *bb):
        out = jnp.einsum("...i,oij,...j->...o", a, w, b)
        if bb:
            out = out + bb[0]
        return out

    return apply(f, ins, name="bilinear")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = coerce(x)
    r = downscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            return a.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        return a.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // r, w // r, c * r * r)

    return apply(f, [x], name="pixel_unshuffle")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    x = coerce(x)
    pl, pr, pt, pb = (padding, padding, padding, padding) if isinstance(padding, int) else padding

    def f(a):
        if data_format == "NCHW":
            return jnp.pad(a, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        return jnp.pad(a, ((0, 0), (pt, pb), (pl, pr), (0, 0)))

    return apply(f, [x], name="zeropad2d")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im (reference: F.fold): x [N, C*kh*kw, L] -> [N, C, H, W] with
    overlapping windows SUMMED — expressed as a scatter-add XLA handles."""
    x = coerce(x)
    oh, ow = _tuplize(output_sizes, 2)
    kh, kw = _tuplize(kernel_sizes, 2)
    sh, sw = _tuplize(strides, 2)
    ph, pw = _tuplize(paddings, 2)
    dh, dw = _tuplize(dilations, 2)
    n_h = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    n_w = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1

    def f(a):
        n, ckk, L = a.shape
        c = ckk // (kh * kw)
        cols = a.reshape(n, c, kh, kw, n_h, n_w)
        # absolute row/col for every (kernel pos, window) pair, padded coords
        ih = (jnp.arange(kh) * dh)[:, None] + (jnp.arange(n_h) * sh)[None, :]  # [kh, n_h]
        iw = (jnp.arange(kw) * dw)[:, None] + (jnp.arange(n_w) * sw)[None, :]
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), a.dtype)
        flat_idx = (
            ih[:, None, :, None] * (ow + 2 * pw) + iw[None, :, None, :]
        ).reshape(-1)  # [kh*kw*n_h*n_w]
        vals = cols.reshape(n, c, -1)
        out = out.reshape(n, c, -1).at[:, :, flat_idx].add(vals)
        out = out.reshape(n, c, oh + 2 * ph, ow + 2 * pw)
        return out[:, :, ph : ph + oh, pw : pw + ow]

    return apply(f, [x], name="fold")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """Connectionist Temporal Classification (reference: F.ctc_loss over
    warpctc).  TPU-native: the standard alpha recursion in log space as a
    lax.scan over time — static shapes, batched over B.

    log_probs: [T, B, C] (paddle layout), labels: [B, S] int32 padded,
    input_lengths/label_lengths: [B]."""
    log_probs, labels = coerce(log_probs), coerce(labels)
    input_lengths, label_lengths = coerce(input_lengths), coerce(label_lengths)

    def f(lp, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), -1)
        T, B, C = lp.shape
        S = lab.shape[1]
        # extended label sequence with interleaved blanks: length 2S+1
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        Lext = 2 * lab_len.astype(jnp.int32) + 1  # [B]
        NEG = -1e30

        # emission log-prob of each extended symbol at each time
        def emit(t_lp):  # [B, C] -> [B, 2S+1]
            return jnp.take_along_axis(t_lp, ext, axis=1)

        # allowed skip: ext[s] != ext[s-2] (and s >= 2)
        skip_ok = jnp.concatenate(
            [jnp.zeros((B, 2), bool), ext[:, 2:] != ext[:, :-2]], axis=1
        )

        alpha0 = jnp.full((B, 2 * S + 1), NEG)
        alpha0 = alpha0.at[:, 0].set(emit(lp[0])[:, 0])
        alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, emit(lp[0])[:, 1], NEG))

        def step(alpha, t):
            prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], 1)
            prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], 1)
            prev2 = jnp.where(skip_ok, prev2, NEG)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
            new = merged + emit(lp[t])
            # freeze past each sequence's input length
            new = jnp.where((t < in_len)[:, None], new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        idx_last = jnp.maximum(Lext - 1, 0)
        idx_prev = jnp.maximum(Lext - 2, 0)
        a_last = jnp.take_along_axis(alpha, idx_last[:, None], 1)[:, 0]
        a_prev = jnp.where(
            Lext >= 2, jnp.take_along_axis(alpha, idx_prev[:, None], 1)[:, 0], NEG
        )
        nll = -jnp.logaddexp(a_last, a_prev)
        if norm_by_times:
            nll = nll / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        if reduction == "mean":
            return (nll / jnp.maximum(lab_len.astype(jnp.float32), 1.0)).mean()
        if reduction == "sum":
            return nll.sum()
        return nll

    return apply(f, [log_probs, labels, input_lengths, label_lengths], name="ctc_loss")


def _pool3d_spec(kernel_size, stride, padding, ndhwc):
    k = _tuplize(kernel_size, 3)
    s = _tuplize(stride if stride is not None else kernel_size, 3)
    pad = _conv_padding(padding, 3, s, k, (1, 1, 1))
    if isinstance(pad, str):
        pad_spec = pad
    elif ndhwc:
        pad_spec = [(0, 0)] + list(pad) + [(0, 0)]
    else:
        pad_spec = [(0, 0), (0, 0)] + list(pad)
    dims = (1,) + k + (1,) if ndhwc else (1, 1) + k
    strides = (1,) + s + (1,) if ndhwc else (1, 1) + s
    return k, pad_spec, dims, strides


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCDHW", name=None):
    x = coerce(x)
    k, pad_spec, dims, strides = _pool3d_spec(kernel_size, stride, padding, data_format == "NDHWC")

    def f(a):
        init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
        return lax.reduce_window(a, init, lax.max, dims, strides, pad_spec)

    out = apply(f, [x], name="max_pool3d")
    if return_mask:
        idx = apply(lambda a: jnp.zeros_like(a, jnp.int32), [out.detach()])
        return out, idx
    return out


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    x = coerce(x)
    k, pad_spec, dims, strides = _pool3d_spec(kernel_size, stride, padding, data_format == "NDHWC")

    def f(a):
        summed = lax.reduce_window(a, 0.0, lax.add, dims, strides, pad_spec)
        if divisor_override:
            return summed / divisor_override
        if exclusive and not isinstance(pad_spec, str):
            counts = lax.reduce_window(jnp.ones_like(a), 0.0, lax.add, dims, strides, pad_spec)
            return summed / counts
        return summed / (k[0] * k[1] * k[2])

    return apply(f, [x], name="avg_pool3d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    x = coerce(x)
    od, oh, ow = _tuplize(output_size, 3)

    def f(a):
        n, c, d, h, w = a.shape
        if d % od == 0 and h % oh == 0 and w % ow == 0:
            return a.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow).mean((3, 5, 7))
        raise NotImplementedError("adaptive_avg_pool3d needs divisible sizes")

    return apply(f, [x], name="adaptive_avg_pool3d")


def conv3d_transpose(
    x, weight, bias=None, stride=1, padding=0, output_padding=0,
    groups=1, dilation=1, data_format="NCDHW", output_size=None, name=None,
):
    """3-D transposed conv via input (lhs) dilation — the 2-D path's
    formulation lifted to DHW.  weight: [in, out, kd, kh, kw]."""
    if groups != 1:
        raise NotImplementedError("conv3d_transpose: groups > 1 not supported")
    if output_size is not None:
        raise NotImplementedError(
            "conv3d_transpose: output_size not supported; use output_padding"
        )
    x, weight = coerce(x), coerce(weight)
    ins = [x, weight]
    if bias is not None:
        ins.append(coerce(bias))
    ins = amp_cast_inputs(ins, "white")
    strides = _tuplize(stride, 3)
    dil = _tuplize(dilation, 3)
    pad = _conv_padding(padding, 3, strides, None, dil)
    op = _tuplize(output_padding, 3)

    def f(a, w, *b):
        ks = w.shape[2:]
        if isinstance(pad, str):
            raise NotImplementedError("conv3d_transpose: string padding unsupported")
        pairs = [
            (dil[i] * (ks[i] - 1) - pad[i][0], dil[i] * (ks[i] - 1) - pad[i][1] + op[i])
            for i in range(3)
        ]
        w2 = jnp.transpose(jnp.flip(w, (2, 3, 4)), (1, 0, 2, 3, 4))
        out = lax.conv_general_dilated(
            a, w2, window_strides=(1, 1, 1), padding=pairs,
            lhs_dilation=strides, rhs_dilation=dil,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        )
        if b:
            out = out + b[0].reshape([1, -1, 1, 1, 1])
        return out

    return apply(f, ins, name="conv3d_transpose")


# ---------------------------------------------------------------------------
# round-5 long tail (reference python/paddle/nn/functional/)
# ---------------------------------------------------------------------------


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    """NCL adaptive max pool (reference: F.adaptive_max_pool1d)."""
    if return_mask:
        raise NotImplementedError("adaptive_max_pool1d: return_mask unsupported")
    x = coerce(x)
    o = int(output_size) if not isinstance(output_size, (list, tuple)) else int(output_size[0])

    def f(a):
        n, c, l = a.shape
        if l % o == 0:
            return a.reshape(n, c, o, l // o).max(-1)
        segs = [a[:, :, (i * l) // o : ((i + 1) * l + o - 1) // o].max(2, keepdims=True) for i in range(o)]
        return jnp.concatenate(segs, 2)

    return apply(f, [x], name="adaptive_max_pool1d")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """Shuffle channels across groups (reference: F.channel_shuffle)."""
    x = coerce(x)

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, groups, c // groups, h, w).swapaxes(1, 2).reshape(a.shape)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, groups, c // groups).swapaxes(3, 4).reshape(a.shape)

    return apply(f, [x], name="channel_shuffle")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0, data_format="NCL", output_size=None, name=None):
    """Inverse of max_pool1d via the pooling indices (reference:
    F.max_unpool1d)."""
    x, indices = coerce(x), coerce(indices)
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    st = k if stride is None else (stride if isinstance(stride, int) else stride[0])
    out_l = (
        int(output_size[-1]) if output_size is not None
        else (x.shape[-1] - 1) * st + k - 2 * padding
    )

    def f(a, idx):
        n, c, l = a.shape
        flat = jnp.zeros((n, c, out_l), a.dtype)
        return flat.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None], idx
        ].set(a)

    return apply(f, [x, indices], name="max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW", output_size=None, name=None):
    """Inverse of max_pool2d via flattened HW indices (reference:
    F.max_unpool2d)."""
    x, indices = coerce(x), coerce(indices)
    kh, kw = _tuplize(kernel_size, 2)
    sh, sw = (kh, kw) if stride is None else _tuplize(stride, 2)
    ph, pw = _tuplize(padding, 2)
    if output_size is not None:
        oh, ow = int(output_size[-2]), int(output_size[-1])
    else:
        oh = (x.shape[-2] - 1) * sh + kh - 2 * ph
        ow = (x.shape[-1] - 1) * sw + kw - 2 * pw

    def f(a, idx):
        n, c, h, w = a.shape
        flat = jnp.zeros((n, c, oh * ow), a.dtype)
        flat = flat.at[
            jnp.arange(n)[:, None, None],
            jnp.arange(c)[None, :, None],
            idx.reshape(n, c, h * w),
        ].set(a.reshape(n, c, h * w))
        return flat.reshape(n, c, oh, ow)

    return apply(f, [x, indices], name="max_unpool2d")


def soft_margin_loss(input, label, reduction="mean", name=None):
    """log(1 + exp(-label * input)) (reference: F.soft_margin_loss)."""
    input, label = coerce(input), coerce(label)
    v = apply(
        lambda a, y: jnp.log1p(jnp.exp(-y.astype(a.dtype) * a)),
        [input, label], name="soft_margin_loss",
    )
    return _reduce(v, reduction)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    """Per-class BCE-with-logits averaged over classes (reference:
    F.multi_label_soft_margin_loss)."""
    input, label = coerce(input), coerce(label)
    ins = [input, label] + ([coerce(weight)] if weight is not None else [])

    def f(a, y, *w):
        y = y.astype(a.dtype)
        per = y * jax.nn.log_sigmoid(a) + (1 - y) * jax.nn.log_sigmoid(-a)
        if w:
            per = per * w[0]
        return -per.mean(-1)

    return _reduce(apply(f, ins, name="multi_label_soft_margin_loss"), reduction)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8, reduction="mean", name=None):
    """Poisson NLL (reference: F.poisson_nll_loss)."""
    input, label = coerce(input), coerce(label)

    def f(a, y):
        y = y.astype(a.dtype)
        if log_input:
            v = jnp.exp(a) - y * a
        else:
            v = a - y * jnp.log(a + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon))
            v = v + jnp.where(y > 1, stirling, 0.0)
        return v

    return _reduce(apply(f, [input, label], name="poisson_nll_loss"), reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6, reduction="mean", name=None):
    """Gaussian NLL with predicted variance (reference: F.gaussian_nll_loss)."""
    input, label, variance = coerce(input), coerce(label), coerce(variance)

    def f(mu, y, var):
        var = jnp.clip(var, epsilon, None)
        v = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            v = v + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, v.dtype))
        return v

    return _reduce(apply(f, [input, label, variance], name="gaussian_nll_loss"), reduction)


def triplet_margin_with_distance_loss(input, positive, negative, distance_function=None, margin=1.0, swap=False, reduction="mean", name=None):
    """Triplet loss with a custom distance callable (reference:
    F.triplet_margin_with_distance_loss)."""
    from ... import ops as _ops

    if distance_function is None:
        distance_function = lambda a, b: pairwise_distance(a, b)  # noqa: E731
    d_pos = distance_function(coerce(input), coerce(positive))
    d_neg = distance_function(coerce(input), coerce(negative))
    if swap:
        d_pn = distance_function(coerce(positive), coerce(negative))
        d_neg = _ops.minimum(d_neg, d_pn)
    v = _ops.clip(d_pos - d_neg + margin, min=0.0)
    return _reduce(v, reduction)
