"""Weight initializers (reference: python/paddle/nn/initializer/)."""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import core as _core
from ..framework.random import default_generator
from ..tensor import Tensor


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, _core.to_jax_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        k = default_generator.next_key()
        return self.mean + self.std * jax.random.normal(k, shape, _core.to_jax_dtype(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        k = default_generator.next_key()
        return self.mean + self.std * jax.random.truncated_normal(
            k, self.a, self.b, shape, _core.to_jax_dtype(dtype)
        )


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        k = default_generator.next_key()
        return jax.random.uniform(
            k, shape, _core.to_jax_dtype(dtype), minval=self.low, maxval=self.high
        )


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle Linear weight is [in, out]
        return shape[0], shape[1]
    # conv: [out_c, in_c, *k]
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = default_generator.next_key()
        return std * jax.random.normal(k, shape, _core.to_jax_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = default_generator.next_key()
        return jax.random.uniform(
            k, shape, _core.to_jax_dtype(dtype), minval=-limit, maxval=limit
        )


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        std = math.sqrt(2.0 / fi)
        k = default_generator.next_key()
        return std * jax.random.normal(k, shape, _core.to_jax_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        limit = math.sqrt(6.0 / fi)
        k = default_generator.next_key()
        return jax.random.uniform(
            k, shape, _core.to_jax_dtype(dtype), minval=-limit, maxval=limit
        )


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), _core.to_jax_dtype(dtype))
        return arr.reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        k = default_generator.next_key()
        return self.gain * jax.nn.initializers.orthogonal()(k, shape, _core.to_jax_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        w = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic)):
            w[(i, i) + tuple(centers)] = 1.0
        return jnp.asarray(w, _core.to_jax_dtype(dtype))


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = param if param is not None else 0.01
        return math.sqrt(2.0 / (1 + a * a))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


_global_weight_init = None
_global_bias_init = None
