"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..framework import core as _core
from ..tensor import Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=I.Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x,
            self._mean,
            self._variance,
            self.weight,
            self.bias,
            training=self.training,
            momentum=self._momentum,
            epsilon=self._epsilon,
            data_format=self._data_format,
            use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.

    Under GSPMD step-compilation the batch axis is sharded and XLA computes
    global statistics automatically when the reduction spans the full batch
    (reference: paddle/phi/kernels/gpu/sync_batch_norm_kernel.cu uses NCCL).
    Eagerly it behaves like BatchNorm over the local shard.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(
                layer._num_features, layer._momentum, layer._epsilon,
                data_format=layer._data_format,
            )
            if layer.weight is not None:
                out.weight._data = layer.weight._data
            if layer.bias is not None:
                out.bias._data = layer.bias._data
            out._mean._data = layer._mean._data
            out._variance._data = layer._variance._data
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0)
            )
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """TPU-native first-class RMSNorm (Llama family standard)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=I.Constant(1.0)
        )

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter([num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter([num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        else:
            self.weight = self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format, name)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k)


class SpectralNorm(Layer):
    """Spectral normalization (reference: python/paddle/nn/layer/norm.py
    SpectralNorm over the spectral_norm op): weight / sigma_max(weight),
    sigma estimated by power iteration on persisted u/v buffers."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, name=None):
        super().__init__()
        import jax

        from ..framework.random import default_generator

        self.dim = dim
        self.power_iters = power_iters
        self.epsilon = epsilon
        h = int(weight_shape[dim])
        w = int(np.prod(weight_shape)) // h
        ku, kv = jax.random.split(default_generator.next_key())
        u = jax.random.normal(ku, (h,), jnp.float32)
        v = jax.random.normal(kv, (w,), jnp.float32)
        self.weight_u = self.create_parameter([h], default_initializer=I.Assign(u / (jnp.linalg.norm(u) + epsilon)))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter([w], default_initializer=I.Assign(v / (jnp.linalg.norm(v) + epsilon)))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax

        from ..ops.dispatch import apply, coerce

        weight = coerce(weight)
        dim, iters, eps = self.dim, self.power_iters, self.epsilon

        def f(w_arr, u, v):
            mat = jnp.moveaxis(w_arr, dim, 0).reshape(w_arr.shape[dim], -1).astype(jnp.float32)
            # the reference's spectral_norm_grad treats u/v as CONSTANTS:
            # iterate on a stop_gradient view so the backward is d(W/sigma)
            # with fixed singular vectors, not a power_iters-deep chain
            mat_ng = jax.lax.stop_gradient(mat)
            for _ in range(iters):
                v = mat_ng.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat_ng @ v
                u = u / (jnp.linalg.norm(u) + eps)
            u = jax.lax.stop_gradient(u)
            v = jax.lax.stop_gradient(v)
            sigma = u @ mat @ v
            return (w_arr / sigma.astype(w_arr.dtype)), u, v

        out, u_new, v_new = apply(
            f,
            [weight, self.weight_u, self.weight_v],
            multi=True,
            name="spectral_norm",
            outputs_stop_gradient=[weight.stop_gradient, True, True],
        )
        if self.training:
            # like BN running stats, u/v only advance in train mode (eval
            # must be deterministic and must not dirty the state_dict)
            self.weight_u._data = u_new._data
            self.weight_v._data = v_new._data
        return out
