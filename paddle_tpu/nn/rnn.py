"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

TPU-native: the whole sequence recurrence is a single op built on lax.scan,
so XLA compiles one fused loop (the reference dispatches per-timestep cuDNN
kernels); autograd flows through scan's built-in VJP.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.dispatch import apply, coerce
from ..tensor import Tensor
from . import initializer as I
from .layer import Layer


def _uniform_init(k):
    return I.Uniform(-k, k)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirectional else 1
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}[mode]
        k = 1.0 / math.sqrt(hidden_size)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                w_ih = self.create_parameter([gate_mult * hidden_size, in_sz], attr=weight_ih_attr, default_initializer=_uniform_init(k))
                w_hh = self.create_parameter([gate_mult * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=_uniform_init(k))
                b_ih = self.create_parameter([gate_mult * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=_uniform_init(k))
                b_hh = self.create_parameter([gate_mult * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=_uniform_init(k))
                self.add_parameter(f"weight_ih{sfx}", w_ih)
                self.add_parameter(f"weight_hh{sfx}", w_hh)
                self.add_parameter(f"bias_ih{sfx}", b_ih)
                self.add_parameter(f"bias_hh{sfx}", b_hh)
                self._all_weights.append((f"weight_ih{sfx}", f"weight_hh{sfx}", f"bias_ih{sfx}", f"bias_hh{sfx}"))

    def _cell(self, mode):
        hs = self.hidden_size

        if mode == "LSTM":
            def step(carry, x_t, w_ih, w_hh, b_ih, b_hh):
                h, c = carry
                gates = x_t @ w_ih.T + h @ w_hh.T + b_ih + b_hh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
                g = jnp.tanh(g)
                c_new = f * c + i * g
                h_new = o * jnp.tanh(c_new)
                return (h_new, c_new), h_new
        elif mode == "GRU":
            def step(carry, x_t, w_ih, w_hh, b_ih, b_hh):
                (h,) = carry
                gi = x_t @ w_ih.T + b_ih
                gh = h @ w_hh.T + b_hh
                i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
                h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(i_r + h_r)
                z = jax.nn.sigmoid(i_z + h_z)
                n = jnp.tanh(i_n + r * h_n)
                h_new = (1 - z) * n + z * h
                return (h_new,), h_new
        else:
            act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu

            def step(carry, x_t, w_ih, w_hh, b_ih, b_hh):
                (h,) = carry
                h_new = act(x_t @ w_ih.T + h @ w_hh.T + b_ih + b_hh)
                return (h_new,), h_new

        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        inputs = coerce(inputs)
        num_dirs = 2 if self.bidirectional else 1
        is_lstm = self.mode == "LSTM"
        batch_axis = 1 if self.time_major else 0

        weights = []
        for names in self._all_weights:
            weights.extend(self._parameters[n] for n in names)

        b = inputs.shape[batch_axis]
        hs = self.hidden_size
        nl = self.num_layers

        init_given = initial_states is not None
        ins = [inputs] + weights
        if init_given:
            if is_lstm:
                h0, c0 = initial_states
                ins += [coerce(h0), coerce(c0)]
            else:
                ins.append(coerce(initial_states))

        mode = self.mode
        time_major = self.time_major
        step_fn = self._cell(mode)

        def f(x, *rest):
            if init_given:
                if is_lstm:
                    wts, (h0_, c0_) = rest[:-2], rest[-2:]
                else:
                    wts, h0_ = rest[:-1], rest[-1]
                    c0_ = None
            else:
                wts = rest
                h0_ = jnp.zeros((nl * num_dirs, b, hs), x.dtype)
                c0_ = jnp.zeros((nl * num_dirs, b, hs), x.dtype) if is_lstm else None

            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # [seq, batch, feat]

            out = x
            final_h = []
            final_c = []
            for layer in range(nl):
                dir_outs = []
                for d in range(num_dirs):
                    idx = (layer * num_dirs + d) * 4
                    w_ih, w_hh, b_ih, b_hh = wts[idx : idx + 4]
                    sid = layer * num_dirs + d
                    h_init = h0_[sid]
                    carry0 = (h_init, c0_[sid]) if is_lstm else (h_init,)
                    seq = jnp.flip(out, 0) if d == 1 else out

                    def scan_step(carry, x_t, _w_ih=w_ih, _w_hh=w_hh, _b_ih=b_ih, _b_hh=b_hh):
                        return step_fn(carry, x_t, _w_ih, _w_hh, _b_ih, _b_hh)

                    carry_f, ys = lax.scan(scan_step, carry0, seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    dir_outs.append(ys)
                    final_h.append(carry_f[0])
                    if is_lstm:
                        final_c.append(carry_f[1])
                out = jnp.concatenate(dir_outs, -1) if num_dirs == 2 else dir_outs[0]
            fh = jnp.stack(final_h, 0)
            if not time_major:
                out = jnp.swapaxes(out, 0, 1)
            if is_lstm:
                return out, fh, jnp.stack(final_c, 0)
            return out, fh

        if is_lstm:
            out, fh, fc = apply(f, ins, multi=True, name=mode.lower())
            return out, (fh, fc)
        out, fh = apply(f, ins, multi=True, name=mode.lower())
        return out, fh


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        k = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=_uniform_init(k))
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=_uniform_init(k))
        self.bias_ih = self.create_parameter([4 * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=_uniform_init(k))
        self.bias_hh = self.create_parameter([4 * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=_uniform_init(k))

    def forward(self, inputs, states=None):
        inputs = coerce(inputs)
        if states is None:
            from .. import ops as _ops

            b = inputs.shape[0]
            states = (
                _ops.zeros([b, self.hidden_size], inputs.dtype),
                _ops.zeros([b, self.hidden_size], inputs.dtype),
            )
        h, c = states
        ins = [inputs, coerce(h), coerce(c), self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

        def f(x, h, c, w_ih, w_hh, b_ih, b_hh):
            gates = x @ w_ih.T + h @ w_hh.T + b_ih + b_hh
            i, fg, g, o = jnp.split(gates, 4, axis=-1)
            i, fg, o = jax.nn.sigmoid(i), jax.nn.sigmoid(fg), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = fg * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        h_new, c_new = apply(f, ins, multi=True, name="lstm_cell")
        return h_new, (h_new, c_new)


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        k = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=_uniform_init(k))
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=_uniform_init(k))
        self.bias_ih = self.create_parameter([3 * hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=_uniform_init(k))
        self.bias_hh = self.create_parameter([3 * hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=_uniform_init(k))

    def forward(self, inputs, states=None):
        inputs = coerce(inputs)
        if states is None:
            from .. import ops as _ops

            states = _ops.zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
        ins = [inputs, coerce(states), self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

        def f(x, h, w_ih, w_hh, b_ih, b_hh):
            gi = x @ w_ih.T + b_ih
            gh = h @ w_hh.T + b_hh
            i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
            h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n + r * h_n)
            return (1 - z) * n + z * h

        h_new = apply(f, ins, name="gru_cell")
        return h_new, h_new


class SimpleRNNCell(Layer):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        k = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter([hidden_size, input_size], attr=weight_ih_attr, default_initializer=_uniform_init(k))
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=_uniform_init(k))
        self.bias_ih = self.create_parameter([hidden_size], attr=bias_ih_attr, is_bias=True, default_initializer=_uniform_init(k))
        self.bias_hh = self.create_parameter([hidden_size], attr=bias_hh_attr, is_bias=True, default_initializer=_uniform_init(k))

    def forward(self, inputs, states=None):
        inputs = coerce(inputs)
        if states is None:
            from .. import ops as _ops

            states = _ops.zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        ins = [inputs, coerce(states), self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]
        h_new = apply(
            lambda x, h, wi, wh, bi, bh: act(x @ wi.T + h @ wh.T + bi + bh), ins, name="rnn_cell"
        )
        return h_new, h_new


class RNN(Layer):
    """Wraps a cell into a recurrence (paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        inputs = coerce(inputs)
        axis = 0 if self.time_major else 1
        steps = inputs.shape[axis]
        rng = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        outs = []
        states = initial_states
        from .. import ops as _ops

        for t in rng:
            x_t = _ops.slice(inputs, [axis], [t], [t + 1]).squeeze([axis])
            y, states = self.cell(x_t, states)
            outs.append(y)
        if self.is_reverse:
            outs = outs[::-1]
        out = _ops.stack(outs, axis=axis)
        return out, states


class RNNCellBase(Layer):
    """Base for custom RNN cells (reference: paddle.nn.RNNCellBase)."""

    def get_initial_states(self, batch_ref, shape=None, dtype="float32", init_value=0.0, batch_dim_idx=0):
        import numpy as np

        from ..ops.dispatch import coerce, wrap
        import jax.numpy as jnp

        b = coerce(batch_ref).shape[batch_dim_idx]
        from ..framework import core as _core

        if shape is None:
            # reference contract: subclasses define state_shape
            shape = getattr(self, "state_shape", None)
            if shape is None:
                hs = getattr(self, "hidden_size", None)
                if hs is None:
                    raise ValueError(
                        "get_initial_states needs `shape`, or the cell must "
                        "define `state_shape` (or `hidden_size`)"
                    )
                shape = [hs]
        shp = [b] + list(shape)
        return wrap(jnp.full(shp, init_value, _core.to_jax_dtype(dtype)))
