"""paddle.metric (reference: python/paddle/metric/metrics.py)."""

from __future__ import annotations

import numpy as np

from . import ops
from .tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, *args):
        return args

    def update_on_device(self, pred, label):
        """Accumulate one batch WITHOUT a host sync: running sums/counts
        stay device-resident (jax scalars) and are reduced to Python floats
        only when ``accumulate()`` is read.  Returns True when this metric
        handled the batch on device; False sends the caller down the
        classic ``compute``/``update`` host path.  The base class has no
        device path."""
        return False


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)
        self._dev_total = None  # per-k jax scalars (update_on_device path)
        self._dev_count = [0] * len(self.topk)

    def update_on_device(self, pred, label):
        """Device-side top-k accuracy: the correctness sums stay jax
        scalars (the per-batch count is static, derived from shapes), so a
        training loop that only READS accuracy at log boundaries never
        syncs per step.  Mirrors compute()+update() numerics exactly
        (same argsort tie-breaking)."""
        import jax
        import jax.numpy as jnp

        p = pred._raw if isinstance(pred, Tensor) else pred
        l = label._raw if isinstance(label, Tensor) else label
        if isinstance(p, jax.core.Tracer) or isinstance(l, jax.core.Tracer):
            return False  # inside a trace host-side sums can't accumulate
        try:
            p = jnp.asarray(p)
            l = jnp.asarray(l)
        except TypeError:
            return False
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        top = jnp.argsort(-p, axis=-1)[..., : self.maxk]
        correct = (top == l[..., None]).astype(jnp.float32)
        n = int(np.prod(correct.shape[:-1]))
        if self._dev_total is None:
            self._dev_total = [jnp.zeros((), jnp.float32) for _ in self.topk]
        for i, k in enumerate(self.topk):
            self._dev_total[i] = self._dev_total[i] + correct[..., :k].sum()
            self._dev_count[i] += n
        return True

    def _fold_device(self):
        """Reduce the device-resident sums into the host totals — ONE
        stacked host fetch for all k, paid only when accumulate() is read."""
        if self._dev_total is None:
            return
        import jax.numpy as jnp

        vals = np.asarray(jnp.stack(self._dev_total))
        for i, v in enumerate(vals):
            self.total[i] += float(v)
            self.count[i] += self._dev_count[i]
        self._dev_total = None
        self._dev_count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        label_np = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        top = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = top == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = correct.numpy() if isinstance(correct, Tensor) else np.asarray(correct)
        n = c.shape[0] if c.ndim else 1
        accs = []
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum()
            self.total[i] += float(num)
            self.count[i] += int(np.prod(c.shape[:-1]))
            accs.append(float(num) / max(int(np.prod(c.shape[:-1])), 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        self._fold_device()
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)) > 0.5
        l = (labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)).astype(bool)
        self.tp += int(np.sum(p & l))
        self.fp += int(np.sum(p & ~l))

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)) > 0.5
        l = (labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)).astype(bool)
        self.tp += int(np.sum(p & l))
        self.fn += int(np.sum(~p & l))

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        if p.ndim == 2:
            p = p[:, -1]
        l = l.reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(int), self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            area += self._stat_pos[i] * (neg + self._stat_neg[i] / 2)
            pos += self._stat_pos[i]
            neg += self._stat_neg[i]
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pred = input.numpy() if isinstance(input, Tensor) else np.asarray(input)
    lab = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
    if lab.ndim == 2 and lab.shape[1] == 1:
        lab = lab[:, 0]
    topk = np.argsort(-pred, axis=-1)[:, :k]
    acc = float(np.mean((topk == lab[:, None]).any(-1)))
    return Tensor(np.asarray(acc, np.float32))
