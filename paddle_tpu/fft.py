"""paddle.fft — discrete Fourier transform family (reference:
python/paddle/fft.py, which wraps phi's cuFFT/onednn FFT kernels).
TPU-native: every transform lowers through jnp.fft onto XLA's FFT HLO,
with the reference's axis/n/norm surface and autograd through the
dispatch layer (XLA differentiates FFT natively).

Norm conventions match the reference (and numpy): "backward" scales the
inverse by 1/n, "ortho" scales both by 1/sqrt(n), "forward" scales the
forward by 1/n.
"""

from __future__ import annotations

import numpy as np

from .ops.dispatch import apply, coerce
from .tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftshift", "ifftshift", "fftfreq", "rfftfreq",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(f"norm must be one of {_NORMS}, got {norm!r}")
    return norm


def _unary_fft(jnp_name, x, extra_kwargs, name):
    import jax.numpy as jnp

    x = coerce(x)
    fn = getattr(jnp.fft, jnp_name)
    return apply(lambda a: fn(a, **extra_kwargs), [x], name=name)


def fft(x, n=None, axis=-1, norm="backward", name=None):
    """1-D DFT along `axis` (reference: paddle.fft.fft)."""
    return _unary_fft("fft", x, dict(n=n, axis=axis, norm=_check_norm(norm)), "fft")


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _unary_fft("ifft", x, dict(n=n, axis=axis, norm=_check_norm(norm)), "ifft")


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    """Real-input DFT: output has n//2+1 frequencies along `axis`."""
    return _unary_fft("rfft", x, dict(n=n, axis=axis, norm=_check_norm(norm)), "rfft")


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _unary_fft("irfft", x, dict(n=n, axis=axis, norm=_check_norm(norm)), "irfft")


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    """DFT of a Hermitian-symmetric signal -> real output."""
    return _unary_fft("hfft", x, dict(n=n, axis=axis, norm=_check_norm(norm)), "hfft")


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _unary_fft("ihfft", x, dict(n=n, axis=axis, norm=_check_norm(norm)), "ihfft")


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _unary_fft("fft2", x, dict(s=s, axes=tuple(axes), norm=_check_norm(norm)), "fft2")


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _unary_fft("ifft2", x, dict(s=s, axes=tuple(axes), norm=_check_norm(norm)), "ifft2")


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _unary_fft("rfft2", x, dict(s=s, axes=tuple(axes), norm=_check_norm(norm)), "rfft2")


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _unary_fft("irfft2", x, dict(s=s, axes=tuple(axes), norm=_check_norm(norm)), "irfft2")


def fftn(x, s=None, axes=None, norm="backward", name=None):
    axes = tuple(axes) if axes is not None else None
    return _unary_fft("fftn", x, dict(s=s, axes=axes, norm=_check_norm(norm)), "fftn")


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    axes = tuple(axes) if axes is not None else None
    return _unary_fft("ifftn", x, dict(s=s, axes=axes, norm=_check_norm(norm)), "ifftn")


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    axes = tuple(axes) if axes is not None else None
    return _unary_fft("rfftn", x, dict(s=s, axes=axes, norm=_check_norm(norm)), "rfftn")


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    axes = tuple(axes) if axes is not None else None
    return _unary_fft("irfftn", x, dict(s=s, axes=axes, norm=_check_norm(norm)), "irfftn")


def fftshift(x, axes=None, name=None):
    """Shift the zero-frequency component to the center."""
    import jax.numpy as jnp

    axes = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), [coerce(x)], name="fftshift")


def ifftshift(x, axes=None, name=None):
    import jax.numpy as jnp

    axes = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), [coerce(x)], name="ifftshift")


def fftfreq(n, d=1.0, dtype="float32", name=None):
    """Sample frequencies for fft output (host-computed constant)."""
    from .framework import core as _core

    return Tensor(np.fft.fftfreq(int(n), d).astype(_core.to_jax_dtype(dtype)))


def rfftfreq(n, d=1.0, dtype="float32", name=None):
    from .framework import core as _core

    return Tensor(np.fft.rfftfreq(int(n), d).astype(_core.to_jax_dtype(dtype)))
