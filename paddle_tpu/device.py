"""paddle.device (reference: python/paddle/device/) — device queries, memory
stats (HBM via PJRT memory_stats instead of the reference's CUDA allocator
counters), stream compat shims (XLA owns scheduling)."""

from __future__ import annotations

import jax

from .framework import core as _core
from .framework.core import (  # noqa: F401
    CPUPlace,
    Place,
    TPUPlace,
    get_device,
    set_device,
)


def set_memory_fraction(fraction, device=None):
    """Cap the HBM fraction the process preallocates (reference:
    FLAGS_fraction_of_gpu_memory_to_use over the BFC allocator).

    TPU-native: the allocator is PJRT's; the knob is
    XLA_PYTHON_CLIENT_MEM_FRACTION and it only takes effect BEFORE the
    first jax backend initialization — call this first thing, or set the
    env var in the launcher.  Raises if the backend is already live with a
    different setting rather than silently doing nothing."""
    import os

    import jax

    want = float(fraction)
    live = getattr(getattr(jax._src, "xla_bridge", None), "_backends", None)
    cur = os.environ.get("XLA_PYTHON_CLIENT_MEM_FRACTION")
    already = cur is not None and float(cur) == want
    if live and not already:
        raise RuntimeError(
            "set_memory_fraction must run before the first jax computation "
            "(the PJRT allocator is configured at backend init); set "
            f"XLA_PYTHON_CLIENT_MEM_FRACTION={want} in the environment or "
            "call earlier"
        )
    os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = str(want)


def get_all_device_type():
    kinds = {"cpu"}
    try:
        if jax.devices()[0].platform != "cpu":
            kinds.add("tpu")
    except RuntimeError:
        pass
    return sorted(kinds)


def get_available_device():
    return [f"tpu:{i}" for i in range(_core.device_count("tpu"))] or ["cpu"]


def get_available_custom_device():
    return []


def is_compiled_with_cinn():
    return False


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def device_count():
    return max(_core.device_count("tpu"), 1)


class Stream:
    """Compat shim: XLA's runtime owns stream scheduling on TPU."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        yield

    return _guard()


def synchronize(device=None):
    """Block until all queued work completes (XLA: drain async dispatch)."""
    try:
        for d in jax.devices():
            pass
        import jax.numpy as jnp

        jnp.zeros(()).block_until_ready()
    except RuntimeError:
        pass


class cuda:
    """Namespace mirror of paddle.device.cuda, mapped to the TPU backend."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return _core.device_count("tpu")

    @staticmethod
    def synchronize(device=None):
        synchronize(device)

    @staticmethod
    def current_stream(device=None):
        return Stream(device)

    @staticmethod
    def stream_guard(stream):
        return stream_guard(stream)

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def _mem_stats(device=None):
        devs = jax.devices()
        d = devs[device if isinstance(device, int) else 0]
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:
            pass
        return stats

    @staticmethod
    def memory_allocated(device=None):
        return int(cuda._mem_stats(device).get("bytes_in_use", 0))

    @staticmethod
    def max_memory_allocated(device=None):
        return int(cuda._mem_stats(device).get("peak_bytes_in_use", 0))

    @staticmethod
    def memory_reserved(device=None):
        return int(cuda._mem_stats(device).get("bytes_reserved", cuda.memory_allocated(device)))

    @staticmethod
    def max_memory_reserved(device=None):
        return int(cuda._mem_stats(device).get("peak_bytes_in_use", 0))

    @staticmethod
    def get_device_properties(device=None):
        devs = jax.devices()
        d = devs[device if isinstance(device, int) else 0]

        class _Props:
            name = str(d.device_kind)
            major = 0
            minor = 0
            total_memory = int(cuda._mem_stats(device).get("bytes_limit", 0))
            multi_processor_count = 1

        return _Props()


class tpu(cuda):
    """First-class TPU namespace: paddle_tpu.device.tpu.*"""

    @staticmethod
    def memory_stats(device=None):
        stats = dict(cuda._mem_stats(device))
        from . import native as _native

        stats.update(_native.host_memory_stats())
        return stats
