"""paddle.static compatibility shim (reference: python/paddle/static/).

The reference's static graph (ProgramDesc + StandaloneExecutor) maps onto
traced XLA programs here (SURVEY.md §2.1 "Static framework": the graph IS
the jaxpr/StableHLO traced by jit.to_static).  This shim keeps the
Program/Executor API shape working for user code that builds a forward
function imperatively and runs it through an Executor.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .. import framework
from ..jit import InputSpec  # noqa: F401
from ..tensor import Tensor


class Program:
    """Holds a python callable + captured spec instead of a ProgramDesc."""

    def __init__(self):
        self._build_fn = None
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program

    def __enter__(self):
        return self.main

    def __exit__(self, *exc):
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Placeholder tensor: returns a zero tensor of the given spec; user
    models built functionally should prefer dygraph + to_static."""
    import jax.numpy as jnp

    from ..framework import core as _core

    shape = [1 if (s is None or s < 0) else s for s in shape]
    t = Tensor(jnp.zeros(shape, _core.to_jax_dtype(dtype)))
    t.name = name
    return t


class Executor:
    """Runs a callable captured as the 'program' (reference:
    StandaloneExecutor over InterpreterCore; here the program is re-executed
    through jit-compiled steps)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        feed = feed or {}
        if callable(getattr(program, "_build_fn", None)):
            out = program._build_fn(**{k: Tensor(v) for k, v in feed.items()})
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [o.numpy() if isinstance(o, Tensor) else np.asarray(o) for o in outs]
        if fetch_list:
            return [
                f.numpy() if isinstance(f, Tensor) else np.asarray(f)
                for f in fetch_list
            ]
        return []

    def close(self):
        pass


def cuda_places(device_ids=None):
    return [framework.TPUPlace(i) for i in (device_ids or [0])]


def cpu_places(device_count=1):
    return [framework.CPUPlace(i) for i in range(device_count)]


def device_guard(device=None):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        yield

    return _guard()


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor, program=None, layer=None):
    """Export an inference artifact (reference: paddle.static.save_inference_model).

    The static-graph ProgramDesc does not exist here — the program IS a
    traced StableHLO module — so the exportable unit is a Layer (pass it as
    `program=` or `layer=`) traced at the feed_vars' shapes/dtypes; the
    serialized module + weights land at <path_prefix>.stablehlo /
    .pdiparams (paddle_tpu.inference.export does the work).  fetch_vars is
    accepted for API parity; the exported outputs are the layer's outputs.
    """
    target = layer if layer is not None else program
    if target is None or not (hasattr(target, "eval") and hasattr(target, "state_dict")):
        raise TypeError(
            "save_inference_model needs the model Layer (pass program=<Layer>); "
            "a static ProgramDesc does not exist in this framework — the "
            "traced StableHLO module is the program"
        )
    from ..inference import export as _export

    feed = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    if not feed or any(f is None for f in feed):
        raise TypeError(
            "save_inference_model needs non-empty feed_vars (example input "
            "tensors defining the traced shapes/dtypes)"
        )
    return _export(target, path_prefix, feed)


def load_inference_model(path_prefix, executor):
    """Returns (predictor, feed_names, fetch_names) — the predictor plays
    the reference's (program, feed_target_names, fetch_targets) role; run
    via predictor.run([arrays...])."""
    from ..inference import Predictor

    p = Predictor(path_prefix)
    return p, p.get_input_names(), p.get_output_names()


def set_program_state(program, state):
    pass


class amp:
    from ..amp import decorate as decorate  # noqa

    @staticmethod
    def auto_cast(*a, **k):
        from ..amp import auto_cast as ac

        return ac(*a, **k)


def gradients(targets, inputs, target_gradients=None):
    from ..autograd import grad as _grad

    return _grad(targets, inputs, grad_outputs=target_gradients, retain_graph=True, allow_unused=True)


# dict-aware tensor tree walkers shared with the jit tracer
from ..jit import _flatten_structure as _tree_tensors  # noqa: E402
from ..jit import _rebuild_structure as _tree_restore_jit  # noqa: E402


def _tree_restore(tpl, leaves):
    return _tree_restore_jit(tpl, leaves)


def _record_captures(run):
    """Run `run()` abstractly (jax.eval_shape) with the dispatch
    capture-recorder installed; returns (result, captured leaf Tensors).
    The pass learns output structure and which outer tensors the closures
    read WITHOUT adding any op — or any effect (prints, callbacks) — to the
    outer program; result leaves carry abstract values usable only for
    shape/dtype inspection."""
    from ..ops import dispatch as _dispatch
    from ..ops.dispatch import coerce

    rec = _dispatch._CaptureRecorder()
    box = {}

    def wrapped():
        old = _dispatch._capture_recorder
        _dispatch._capture_recorder = rec
        try:
            out = run()
        finally:
            _dispatch._capture_recorder = old
        box["out"] = out
        sink = []
        _tree_tensors(out, sink)
        box["sink"] = sink
        return tuple(coerce(t)._data for t in sink)

    jax.eval_shape(wrapped)
    captured = rec.captured()
    # Purity contract: the discovery pass ran the block for real at the
    # paddle level, so a block that WRITES to pre-existing state (in-place
    # ops, buffer updates like BatchNorm running stats) has just rebound
    # live tensors to abstract eval_shape values — silent state corruption
    # that surfaces as a baffling tracer error much later.  Diff every
    # pre-existing tensor the block touched against its first-seen payload:
    # restore the original and raise a clear error instead.
    impure = []
    for t in captured:
        snap = rec.snapshots.get(id(t))
        if snap is not None and t._data is not snap:
            t._data = snap  # undo the corruption before raising
            impure.append(getattr(t, "name", None) or f"<{tuple(t.shape)} {t.dtype}>")
    if impure:
        raise ValueError(
            "static control-flow block is impure: it wrote to pre-existing "
            f"tensor(s) {impure[:5]} during the discovery pass. cond/while_loop "
            "branches must be side-effect-free — return new values through "
            "the block's outputs (loop_vars / branch returns) instead of "
            "assigning to captured state (e.g. put BatchNorm layers in eval "
            "mode inside branches). The original payloads were restored."
        )
    # a block may return a pre-existing tensor DIRECTLY (no op touches it,
    # so apply() never records it) — it still needs to be an operand or its
    # gradient is silently lost
    seen = {id(t) for t in captured}
    for t in box["sink"]:
        if id(t) not in rec.created and id(t) not in seen:
            seen.add(id(t))
            captured.append(t)
    return box["out"], captured


def _branch_runner(fn, captured, out_check=None):
    """Build a pure array->arrays function that re-runs the paddle-level
    `fn` under a NESTED execute-trace substituting the captured tensors'
    slots with the given arrays (the same mechanism jit's compiled runner
    uses).  State writes inside go to the nested overlay and are discarded:
    control-flow blocks are pure, as the reference requires."""
    from ..framework import core as _core
    from ..jit import _Trace
    from ..ops.dispatch import coerce

    def run(arrays):
        subst = {(id(t), "data"): a for t, a in zip(captured, arrays)}
        tr = _Trace("execute", subst=subst)
        old = _core.set_active_trace(tr)
        try:
            with _core.no_grad_ctx():
                out = fn() if fn is not None else None
            sink = []
            tpl = _tree_tensors(out, sink)
            if out_check is not None:
                out_check(tpl, sink)
            return tuple(coerce(t)._data for t in sink)
        finally:
            _core.set_active_trace(old)

    return run


class nn:
    """Static-graph control flow (reference: paddle.static.nn.cond /
    while_loop, the ops paddle.jit dy2static lowers `if`/`while` on tensor
    values into — python/paddle/static/nn/control_flow.py).

    TPU-native lowering:
    - cond: with a concrete predicate (dygraph) only the taken branch runs;
      under @to_static tracing it lowers to XLA's `conditional` via
      jax.lax.cond — SINGLE-branch execution at runtime, differentiable,
      with closure-captured tensors lifted to explicit operands so their
      gradients flow.  Branches must be side-effect-free (the reference
      imposes the same purity on cond blocks).
    - while_loop: lax.while_loop over explicit loop_vars (forward-only,
      unbounded); pass `max_iters=` to lower to a lax.scan-based bounded
      loop instead — differentiable through loop_vars AND captures, at the
      cost of always running max_iters masked iterations.
    """

    @staticmethod
    def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
        import numpy as _np

        from ..framework import core as _core
        from ..ops.dispatch import apply, coerce

        pred = coerce(pred)
        concrete = not isinstance(pred._data, jax.core.Tracer)
        if concrete:
            taken = bool(_np.asarray(pred._data))
            fn = true_fn if taken else false_fn
            return fn() if fn is not None else None

        # discovery: run both branches once at the paddle level (dead code
        # in the outer program) to learn output structure + captured tensors
        def _disc():
            t_out = true_fn() if true_fn is not None else None
            f_out = false_fn() if false_fn is not None else None
            return t_out, f_out

        (t_out, f_out), captured = _record_captures(_disc)
        captured = [t for t in captured if t is not pred]
        t_leaves, f_leaves = [], []
        t_tpl = _tree_tensors(t_out, t_leaves)
        f_tpl = _tree_tensors(f_out, f_leaves)
        if t_tpl != f_tpl or len(t_leaves) != len(f_leaves):
            raise ValueError(
                "paddle.static.nn.cond: true_fn and false_fn must return "
                "the same structure of tensors (got {} vs {})".format(t_tpl, f_tpl)
            )
        for tt, ft in zip(t_leaves, f_leaves):
            if tuple(tt.shape) != tuple(ft.shape) or tt.dtype != ft.dtype:
                raise ValueError(
                    "paddle.static.nn.cond: branch outputs must have equal "
                    "shapes/dtypes, got {}/{} vs {}/{}".format(
                        tt.shape, tt.dtype, ft.shape, ft.dtype
                    )
                )

        run_true = _branch_runner(true_fn, captured)
        run_false = _branch_runner(false_fn, captured)

        def f(p, *cap):
            return jax.lax.cond(
                p.reshape(()).astype(bool),
                lambda c: run_true(c),
                lambda c: run_false(c),
                cap,
            )

        outs = apply(f, [pred] + captured, multi=True, name="cond")
        return _tree_restore(t_tpl, list(outs))

    @staticmethod
    def while_loop(cond, body, loop_vars, is_test=False, name=None, max_iters=None):
        from ..framework import core as _core
        from ..jit import _Trace
        from ..ops.dispatch import apply, coerce
        from ..tensor import Tensor

        loop_vars = list(loop_vars)
        leaves = []
        tpl = _tree_tensors(loop_vars, leaves)
        leaves = [coerce(t) for t in leaves]

        def wrap_vals(vals):
            ts = []
            for a in vals:
                t = Tensor.__new__(Tensor)
                t._init_from_array(a, stop_gradient=True)
                ts.append(t)
            return _tree_restore(tpl, ts)

        def out_arrays(out):
            sink = []
            out_tpl = _tree_tensors(list(out), sink)
            if out_tpl != tpl:
                raise ValueError(
                    "paddle.static.nn.while_loop: body must return "
                    "loop_vars-shaped outputs"
                )
            return tuple(coerce(t)._data for t in sink)

        if max_iters is None:
            # unbounded forward-only loop: XLA while is not differentiable
            def f(*arrays):
                def jcond(vals):
                    with _core.no_grad_ctx():
                        r = cond(*wrap_vals(list(vals)))
                    r = coerce(r[0] if isinstance(r, (list, tuple)) else r)
                    return r._data.reshape(())

                def jbody(vals):
                    with _core.no_grad_ctx():
                        out = body(*wrap_vals(list(vals)))
                    return out_arrays(out)

                return jax.lax.while_loop(jcond, jbody, tuple(arrays))

            outs = apply(
                f,
                leaves,
                name="while_loop",
                multi=True,
                outputs_stop_gradient=[True] * len(leaves),
            )
            return list(_tree_restore(tpl, list(outs)))

        # bounded differentiable loop (reference: dy2static while supports
        # grad): lax.scan over max_iters steps with an alive mask — each
        # step computes body(vals) and keeps the old vals once the loop
        # condition has gone false.  Gradients flow through loop_vars and
        # through closure-captured tensors (lifted to operands below).
        def _disc():
            out = body(*loop_vars)
            cond(*loop_vars)
            return out

        _, captured = _record_captures(_disc)
        cap_set = {id(t) for t in leaves}
        captured = [t for t in captured if id(t) not in cap_set]
        n = len(leaves)

        def f(*arrays):
            vals0, caps = arrays[:n], arrays[n:]
            subst_base = {(id(t), "data"): a for t, a in zip(captured, caps)}

            def run_paddle(fn_args_fn):
                tr = _Trace("execute", subst=dict(subst_base))
                old = _core.set_active_trace(tr)
                try:
                    with _core.no_grad_ctx():
                        return fn_args_fn()
                finally:
                    _core.set_active_trace(old)

            def jcond(vals):
                r = run_paddle(lambda: cond(*wrap_vals(list(vals))))
                r = coerce(r[0] if isinstance(r, (list, tuple)) else r)
                return r._data.reshape(()).astype(bool)

            def jbody(vals):
                return run_paddle(lambda: out_arrays(body(*wrap_vals(list(vals)))))

            import jax.numpy as _jnp

            def step(carry, _):
                vals, alive = carry
                new_vals = jbody(vals)
                sel = tuple(
                    _jnp.where(alive, nv, ov) for nv, ov in zip(new_vals, vals)
                )
                alive = alive & jcond(sel)
                return (sel, alive), None

            alive0 = jcond(tuple(vals0))
            (final, _), _ = jax.lax.scan(
                step, (tuple(vals0), alive0), None, length=int(max_iters)
            )
            return final

        outs = apply(f, leaves + captured, name="while_loop_scan", multi=True)
        outs = list(outs)[:n]
        return list(_tree_restore(tpl, outs))

    @staticmethod
    def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
           activation=None, name=None):
        """static.nn.fc (reference: python/paddle/static/nn/common.py fc):
        flatten trailing dims, apply a fresh Linear, optional activation.
        Weights are created per call (the reference keys them into the
        Program; here the imperative nn.Linear owns them — reuse a
        nn.Linear directly for shared weights)."""
        from .. import nn as _nn
        from ..ops.dispatch import coerce

        x = coerce(x)
        if not 1 <= num_flatten_dims < x.ndim:
            raise ValueError(
                f"fc: num_flatten_dims must be in [1, {x.ndim - 1}] for a "
                f"rank-{x.ndim} input, got {num_flatten_dims}"
            )
        flat = 1
        for d in x.shape[num_flatten_dims:]:
            flat *= d
        lead = list(x.shape[:num_flatten_dims])
        layer = _nn.Linear(flat, size, weight_attr=weight_attr, bias_attr=bias_attr)
        out = layer(x.reshape(lead + [flat]))
        if activation is not None:
            import paddle_tpu.nn.functional as F

            out = getattr(F, activation)(out)
        return out
