"""paddle.static compatibility shim (reference: python/paddle/static/).

The reference's static graph (ProgramDesc + StandaloneExecutor) maps onto
traced XLA programs here (SURVEY.md §2.1 "Static framework": the graph IS
the jaxpr/StableHLO traced by jit.to_static).  This shim keeps the
Program/Executor API shape working for user code that builds a forward
function imperatively and runs it through an Executor.
"""

from __future__ import annotations

import numpy as np

from .. import framework
from ..jit import InputSpec  # noqa: F401
from ..tensor import Tensor


class Program:
    """Holds a python callable + captured spec instead of a ProgramDesc."""

    def __init__(self):
        self._build_fn = None
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program

    def __enter__(self):
        return self.main

    def __exit__(self, *exc):
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Placeholder tensor: returns a zero tensor of the given spec; user
    models built functionally should prefer dygraph + to_static."""
    import jax.numpy as jnp

    from ..framework import core as _core

    shape = [1 if (s is None or s < 0) else s for s in shape]
    t = Tensor(jnp.zeros(shape, _core.to_jax_dtype(dtype)))
    t.name = name
    return t


class Executor:
    """Runs a callable captured as the 'program' (reference:
    StandaloneExecutor over InterpreterCore; here the program is re-executed
    through jit-compiled steps)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        feed = feed or {}
        if callable(getattr(program, "_build_fn", None)):
            out = program._build_fn(**{k: Tensor(v) for k, v in feed.items()})
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [o.numpy() if isinstance(o, Tensor) else np.asarray(o) for o in outs]
        if fetch_list:
            return [
                f.numpy() if isinstance(f, Tensor) else np.asarray(f)
                for f in fetch_list
            ]
        return []

    def close(self):
        pass


def cuda_places(device_ids=None):
    return [framework.TPUPlace(i) for i in (device_ids or [0])]


def cpu_places(device_count=1):
    return [framework.CPUPlace(i) for i in range(device_count)]


def device_guard(device=None):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        yield

    return _guard()


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor, program=None, layer=None):
    """Export an inference artifact (reference: paddle.static.save_inference_model).

    The static-graph ProgramDesc does not exist here — the program IS a
    traced StableHLO module — so the exportable unit is a Layer (pass it as
    `program=` or `layer=`) traced at the feed_vars' shapes/dtypes; the
    serialized module + weights land at <path_prefix>.stablehlo /
    .pdiparams (paddle_tpu.inference.export does the work).  fetch_vars is
    accepted for API parity; the exported outputs are the layer's outputs.
    """
    target = layer if layer is not None else program
    if target is None or not (hasattr(target, "eval") and hasattr(target, "state_dict")):
        raise TypeError(
            "save_inference_model needs the model Layer (pass program=<Layer>); "
            "a static ProgramDesc does not exist in this framework — the "
            "traced StableHLO module is the program"
        )
    from ..inference import export as _export

    feed = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    if not feed or any(f is None for f in feed):
        raise TypeError(
            "save_inference_model needs non-empty feed_vars (example input "
            "tensors defining the traced shapes/dtypes)"
        )
    return _export(target, path_prefix, feed)


def load_inference_model(path_prefix, executor):
    """Returns (predictor, feed_names, fetch_names) — the predictor plays
    the reference's (program, feed_target_names, fetch_targets) role; run
    via predictor.run([arrays...])."""
    from ..inference import Predictor

    p = Predictor(path_prefix)
    return p, p.get_input_names(), p.get_output_names()


def set_program_state(program, state):
    pass


class amp:
    from ..amp import decorate as decorate  # noqa

    @staticmethod
    def auto_cast(*a, **k):
        from ..amp import auto_cast as ac

        return ac(*a, **k)


def gradients(targets, inputs, target_gradients=None):
    from ..autograd import grad as _grad

    return _grad(targets, inputs, grad_outputs=target_gradients, retain_graph=True, allow_unused=True)


class nn:
    @staticmethod
    def fc(x, size, **kwargs):
        raise NotImplementedError("static fluid layers are superseded by paddle_tpu.nn")
