"""Core framework state: dtypes, places, devices, global modes.

TPU-native re-design of the reference's platform layer
(paddle/phi/common/place.h, paddle/phi/core/flags.cc — see SURVEY.md §2.1
"Device/platform" / "Flags/config").  Instead of a DeviceContext pool over
CUDA streams, devices are JAX/PJRT devices; `set_device` selects the default
placement for newly created tensors.
"""

from __future__ import annotations

import os
import threading
import contextlib

import numpy as np
import jax
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# dtypes
# ---------------------------------------------------------------------------

_STR2DTYPE = {
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "uint8": jnp.uint8,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "bool": jnp.bool_,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

_ALIASES = {
    "fp16": "float16",
    "bf16": "bfloat16",
    "fp32": "float32",
    "fp64": "float64",
    "half": "float16",
    "float": "float32",
    "double": "float64",
    "int": "int32",
    "long": "int64",
}


def convert_dtype(dtype):
    """Normalize a dtype spec (string / numpy / jnp dtype) to a canonical string."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name not in _STR2DTYPE:
            raise ValueError(f"Unsupported dtype string: {dtype!r}")
        return name
    try:
        name = np.dtype(dtype).name
    except TypeError:
        name = getattr(dtype, "name", None) or str(dtype)
    name = {"bool_": "bool"}.get(name, name)
    if name not in _STR2DTYPE:
        raise ValueError(f"Unsupported dtype: {dtype!r}")
    return name


_X64_DEMOTE = {"int64": jnp.int32, "uint64": jnp.uint32, "float64": jnp.float32}


def to_jax_dtype(dtype):
    if dtype is None:
        return None
    name = convert_dtype(dtype)
    # TPU-native: 32-bit integers/floats by default (x64 disabled) — wide
    # dtypes demote silently, mirroring jax's canonical dtype policy.
    if not jax.config.jax_enable_x64 and name in _X64_DEMOTE:
        return _X64_DEMOTE[name]
    return _STR2DTYPE[name]


def is_floating_dtype(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(to_jax_dtype(convert_dtype(dtype))), jnp.inexact)


_default_dtype = "float32"


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if d not in ("float16", "bfloat16", "float32", "float64"):
        raise TypeError(f"set_default_dtype only supports float dtypes, got {d}")
    _default_dtype = d


def get_default_dtype() -> str:
    return _default_dtype


# ---------------------------------------------------------------------------
# Places / devices
# ---------------------------------------------------------------------------


class Place:
    """Device placement, mirroring the reference's phi::Place taxonomy.

    On this framework a place maps onto a JAX device: ``TPUPlace(i)`` is the
    i-th accelerator chip (PJRT device), ``CPUPlace()`` the host platform.
    """

    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self.device_type, self._device_id))

    def __repr__(self):
        return f"Place({self.device_type}:{self._device_id})"

    # -- JAX bridge ------------------------------------------------------
    def jax_device(self):
        devs = _devices_for(self.device_type)
        if not devs:
            raise RuntimeError(f"No {self.device_type} devices available")
        return devs[self._device_id % len(devs)]


class CPUPlace(Place):
    device_type = "cpu"

    def __repr__(self):
        return "CPUPlace"


class TPUPlace(Place):
    device_type = "tpu"

    def __repr__(self):
        return f"TPUPlace({self._device_id})"


class CUDAPlace(Place):  # accepted for API compat; maps to accelerator if any
    device_type = "gpu"


class CUDAPinnedPlace(CPUPlace):
    pass


def _devices_for(kind: str):
    try:
        if kind == "cpu":
            return jax.devices("cpu")
        # any non-cpu accelerator backend counts as "tpu"/"gpu"
        default = jax.devices()
        if default and default[0].platform != "cpu":
            return default
        return []
    except RuntimeError:
        return []


_current_place = None
_place_lock = threading.Lock()


def _default_place() -> Place:
    devs = jax.devices()
    if devs[0].platform == "cpu":
        return CPUPlace(0)
    return TPUPlace(0)


def get_device() -> str:
    p = _expected_place()
    if isinstance(p, CPUPlace):
        return "cpu"
    return f"{p.device_type}:{p.get_device_id()}"


def _expected_place() -> Place:
    global _current_place
    if _current_place is None:
        with _place_lock:
            if _current_place is None:
                _current_place = _default_place()
    return _current_place


def set_device(device) -> Place:
    """paddle.set_device: 'cpu', 'tpu', 'tpu:0', 'gpu:0' (alias of tpu here)."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return device
    dev = str(device).lower()
    if ":" in dev:
        kind, _, idx = dev.partition(":")
        idx = int(idx)
    else:
        kind, idx = dev, 0
    if kind == "cpu":
        _current_place = CPUPlace(idx)
    elif kind in ("tpu", "xpu"):
        _current_place = TPUPlace(idx)
    elif kind in ("gpu", "cuda"):
        # reference scripts say gpu; route to the accelerator
        _current_place = TPUPlace(idx)
    else:
        raise ValueError(f"Unknown device {device!r}")
    # steer jax's default placement (tensors stay uncommitted so they can
    # combine with mesh-sharded operands)
    try:
        jax.config.update("jax_default_device", _current_place.jax_device())
    except (RuntimeError, ValueError):
        pass
    return _current_place


def device_count(kind: str = "tpu") -> int:
    return len(_devices_for(kind))


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return bool(_devices_for("tpu"))


# ---------------------------------------------------------------------------
# Global execution modes (grad, trace) — thread-local
# ---------------------------------------------------------------------------


class _ModeState(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.trace = None  # active jit trace (paddle_tpu.jit), or None
        self.amp = None  # active amp state (paddle_tpu.amp), or None


_mode = _ModeState()


def grad_enabled() -> bool:
    return _mode.grad_enabled


def set_grad_enabled(flag: bool) -> bool:
    old = _mode.grad_enabled
    _mode.grad_enabled = bool(flag)
    return old


@contextlib.contextmanager
def no_grad_ctx():
    old = set_grad_enabled(False)
    try:
        yield
    finally:
        set_grad_enabled(old)


@contextlib.contextmanager
def enable_grad_ctx():
    old = set_grad_enabled(True)
    try:
        yield
    finally:
        set_grad_enabled(old)


def active_trace():
    return _mode.trace


def set_active_trace(tr):
    old = _mode.trace
    _mode.trace = tr
    return old


# Birth registry: tensors created while a jit trace is active are "trace-born"
# and excluded from implicit state capture (see paddle_tpu/jit).  Side table
# because Tensor uses __slots__.
import weakref as _weakref

_birth = {}  # id(tensor) -> (weakref, trace token)


def mark_born_if_tracing(t):
    tr = _mode.trace
    if tr is not None:
        _birth[id(t)] = (_weakref.ref(t), tr.token)


def unmark_born(t):
    """Declare a tensor created mid-trace as PERSISTENT state: its payload is
    concrete (caller must build it under jax.ensure_compile_time_eval) and it
    participates in state capture like pre-existing tensors."""
    _birth.pop(id(t), None)


def get_born_token(t):
    rec = _birth.get(id(t))
    if rec is None:
        return None
    ref, token = rec
    if ref() is not t:
        _birth.pop(id(t), None)
        return None
    return token


_name_counters: dict = {}


def unique_name(prefix="tensor"):
    """Process-wide unique name generator (reference:
    python/paddle/utils/unique_name.py) — construction-order deterministic, so
    names are stable across processes that build the same model."""
    n = _name_counters.get(prefix, 0)
    _name_counters[prefix] = n + 1
    return f"{prefix}_{n}"


def active_amp():
    return _mode.amp


def set_active_amp(state):
    old = _mode.amp
    _mode.amp = state
    return old


# ---------------------------------------------------------------------------
# Flags registry (reference: PHI_DEFINE_EXPORTED_* gflags, paddle.set_flags)
# ---------------------------------------------------------------------------

_FLAG_DEFS = {}  # name -> (type, default, help)
_flags = {}


def define_flag(name: str, default, help: str = ""):
    _FLAG_DEFS[name] = (type(default), default, help)
    env = os.environ.get(name)
    if env is not None:
        _flags[name] = _parse_flag(type(default), env)
    else:
        _flags[name] = default


def _parse_flag(typ, text):
    if typ is bool:
        return text.lower() in ("1", "true", "yes", "on")
    return typ(text)


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    return {n: _flags[n] for n in names}


def set_flags(flags: dict):
    for k, v in flags.items():
        if k not in _FLAG_DEFS:
            raise KeyError(f"Unknown flag {k!r}")
        typ = _FLAG_DEFS[k][0]
        _flags[k] = _parse_flag(typ, v) if isinstance(v, str) and typ is not str else typ(v)
    if "FLAGS_compile_cache_dir" in flags:
        setup_compile_cache()


def flag(name):
    return _flags[name]


# core flags mirroring the reference's most used ones
define_flag("FLAGS_check_nan_inf", False, "scan op outputs for nan/inf")
define_flag("FLAGS_cudnn_deterministic", False, "deterministic ops (no-op on XLA)")
define_flag("FLAGS_use_stride_kernel", False, "compat only")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92, "compat only; XLA preallocation")
define_flag("FLAGS_eager_delete_tensor_gb", 0.0, "compat only; GC by refcount")
define_flag("FLAGS_log_level", 0, "VLOG level for python-side logging")
define_flag(
    "FLAGS_compile_cache_dir",
    os.environ.get("PADDLE_COMPILE_CACHE_DIR", ""),
    "persistent compilation cache root: XLA binaries (jax persistent cache) "
    "and AOT executable snapshots survive the process, so restarts and "
    "serving cold starts skip recompilation; empty disables",
)
define_flag(
    "FLAGS_eager_cache_max_entries", 4096,
    "LRU bound on the eager dispatch executable cache (ops/dispatch.py)",
)
define_flag(
    "FLAGS_max_inflight_steps", 2,
    "bound on device steps the async hapi train loop keeps in flight before "
    "the host blocks (backpressure without a value transfer); 1 = strict "
    "per-step sync fallback, identical numerics",
)
define_flag(
    "FLAGS_serve_slots", 4,
    "continuous-batching engine: number of KV-cache slots in the pooled "
    "StaticKVCache (max concurrently decoding requests)",
)
define_flag(
    "FLAGS_serve_queue_depth", 32,
    "continuous-batching engine: admission queue bound; submissions beyond "
    "it fail fast (serve() maps this to HTTP 503)",
)
define_flag(
    "FLAGS_serve_prefill_buckets", "16,32,64,128",
    "continuous-batching engine: comma-separated prompt-length buckets; each "
    "bucket compiles one prefill executable (prompts pad up to the bucket)",
)
define_flag(
    "FLAGS_serve_step_timeout_sec", 0.0,
    "serving watchdog deadline (s) for the engine's armed regions (prefill "
    "dispatch, decode dispatch, token fetch); a region overrunning it trips "
    "the EngineSupervisor into a bounded warm engine restart.  0 disables.",
)
define_flag(
    "FLAGS_serve_max_restarts", 3,
    "EngineSupervisor restart budget: after this many engine restarts the "
    "supervisor declares the engine dead and fails all pending requests",
)
define_flag(
    "FLAGS_serve_restart_backoff", 0.5,
    "initial delay (s) before an engine restart, doubled per consecutive "
    "restart (the serving mirror of launch --restart_backoff)",
)
define_flag(
    "FLAGS_serve_drain_grace", 10.0,
    "SIGTERM drain budget (s) for serve(): stop admitting, finish in-flight "
    "up to this long, then exit cleanly.  Overridden by PADDLE_STOP_GRACE "
    "when launched under distributed.launch (--stop_grace).",
)
define_flag(
    "FLAGS_serve_debug_invariants", False,
    "after every scheduler step assert slot-pool invariants (no slot both "
    "free and active, one live request per slot, positions <= max_len) — "
    "turns silent slot leaks into loud failures in tests/CI.  With paged KV "
    "it additionally audits the page pool: refcounts match the slot tables "
    "plus prefix-cache holds, the free list is exact, no page leaks",
)
define_flag(
    "FLAGS_serve_paged_kv", True,
    "continuous-batching engine: back the KV cache with a block-paged pool "
    "(per-slot page tables as traced data) instead of dense per-slot "
    "buffers; False restores the dense slot pool (the bit-identity oracle)",
)
define_flag(
    "FLAGS_serve_kv_page_size", 128,
    "paged KV: tokens per page.  Clamped to the engine max_len; every "
    "sequence holds ceil(len/page_size) pages instead of a dense max_len "
    "row, which is where the concurrency win comes from",
)
define_flag(
    "FLAGS_serve_kv_pool_pages", 0,
    "paged KV: total pages in the pool (page 0 is a permanent scratch page "
    "for masked/inactive writes).  0 = auto: slots * pages_per_seq + 1, the "
    "same HBM budget as the dense slot pool",
)
define_flag(
    "FLAGS_serve_prefix_cache", True,
    "paged KV: keep committed prompt pages in a host-side prefix index so a "
    "request sharing a cached prefix maps those pages read-only (refcounted, "
    "copy-on-write into partially filled pages) and prefills only its "
    "unshared suffix",
)
define_flag(
    "FLAGS_serve_spec_k", 0,
    "paged engine: speculative decoding draft length — an n-gram/prompt-"
    "lookup drafter proposes up to k tokens per greedy slot from the slot's "
    "own prompt+generated history and the target model verifies all k+1 "
    "positions in ONE compiled forward (shaped [slots, k+1]; acceptance is "
    "data, so slot churn still causes zero recompiles).  0 disables "
    "speculation (the plain one-token decode step).  Per-request 'spec_k' "
    "clamps below this engine-wide cap",
)
define_flag(
    "FLAGS_serve_spec_ngram", 3,
    "speculative decoding: longest n-gram the prompt-lookup drafter matches "
    "against the slot's history (it backs off n..1 and proposes nothing on "
    "a miss — a prompt shorter than n just drafts from lower orders)",
)
define_flag(
    "FLAGS_serve_decode_kernel", "auto",
    "paged engine: attention kernel for the paged decode/verify hot path — "
    "'auto' (fused Pallas kernel reading the arena through the page tables "
    "in-kernel when on TPU and the shape is eligible, else gather-then-"
    "dense), 'fused' (require the fused kernel; engine construction fails "
    "if it cannot run), or 'gather' (force the materialized-gather oracle "
    "the fused kernel is parity-tested against)",
)
define_flag(
    "FLAGS_serve_kv_quant", "none",
    "paged engine: KV-cache storage precision — 'none' stores pages in the "
    "model's cache dtype; 'int8' stores K/V pages as int8 with per-token-"
    "row, per-kv-head float32 scales in a parallel scale arena that rides "
    "the same page tables/refcounts/COW/prefix machinery, roughly doubling "
    "the page pool the same HBM budget buys (FLAGS_serve_kv_pool_pages "
    "auto-sizing accounts for the scale bytes).  The fused Pallas decode "
    "kernel dequantizes per page tile in VMEM; the gather oracle applies "
    "the same dequant math",
)
define_flag(
    "FLAGS_serve_tp", 1,
    "tensor-parallel serving: shard the model's column/row-parallel "
    "projections, the paged KV arena (kv_heads axis), and the fused "
    "paged-decode kernel across the first N devices of an 'mp' mesh built "
    "at engine construction.  All per-slot scheduling state stays host-side "
    "and replicated, so the compiled budget and zero-recompile contract are "
    "unchanged; heads/kv_heads must divide by N (typed ShardingError "
    "otherwise).  1 disables (single-device engine, no mesh installed)",
)
define_flag(
    "FLAGS_serve_cp", 1,
    "context-parallel serving (long-context tier): block-shard the paged KV "
    "arena's PAGE axis across N devices of a 'cp' mesh axis (composing with "
    "FLAGS_serve_tp as a cp x mp mesh over the first cp*tp devices).  One "
    "sequence's pages spread round-robin over the shards — sequence page k "
    "lives on shard k % cp — so a 64k-token prompt's KV never has to fit "
    "one device's arena; each shard runs the fused paged-decode kernel "
    "over its local page-table slice and the shards merge per-row online-"
    "softmax partials (m, l, acc) with one pmax + two psums per step.  "
    "Requires the paged engine and role=colocated; pool auto-sizing and "
    "admission headroom become per-shard quantities.  1 disables",
)
define_flag(
    "FLAGS_serve_session_max", 256,
    "session KV (multi-turn serving): maximum resident sessions per engine. "
    "A request carrying 'session_id' pins its committed prompt+generation "
    "pages in the prefix cache so turn N+1 chunk-prefills only the unshared "
    "suffix; sessions beyond this bound (or under page pressure once the "
    "unpinned prefix cache is exhausted) are evicted whole, LRU first.  "
    "Requires the paged engine with the prefix cache enabled",
)
define_flag(
    "FLAGS_serve_role", "colocated",
    "disaggregated serving: role this replica plays in the fleet — "
    "'colocated' (classic single-box engine: prefill and decode on the "
    "same worker), 'prefill' (runs chunked prefill into committed pages "
    "and exports the quantized page rows + prefix-chain metadata as a "
    "handoff payload; never decodes past the first token), or 'decode' "
    "(imports handoff payloads into its own page arena via a compiled "
    "page scatter and streams the remaining tokens).  prefill/decode "
    "roles require the paged engine (the handoff rides the page arenas)",
)
define_flag(
    "FLAGS_serve_reserve_ttl_s", 30.0,
    "disaggregated serving: seconds a decode-side page reservation "
    "(POST /reserve) stays valid before it is reclaimed.  The router "
    "reserves decode pages before prefill starts so the handoff can "
    "never strand a finished prefill with nowhere to land; a crashed "
    "router or dropped handoff simply lets the TTL expire, returning "
    "the reserved headroom to the admission path",
)
define_flag(
    "FLAGS_serve_lora_capacity", 8,
    "multi-tenant LoRA serving: resident-adapter slots in the paged adapter "
    "arena (slot 0 is the pinned base-model passthrough on top of this).  "
    "Residency is refcounted + LRU-evicted exactly like KV pages; a request "
    "naming a non-resident adapter loads it at admission (or parks under "
    "pressure).  Per-slot adapter ids are traced data, so any mix of "
    "resident adapters co-batches in the same compiled decode step",
)
define_flag(
    "FLAGS_serve_lora_rank_max", 8,
    "multi-tenant LoRA serving: the arena's stacked A/B factors are padded "
    "to this rank; registering an adapter with a higher rank than the "
    "engine's arena fails at submit.  Padding columns are zero — exact",
)
define_flag(
    "FLAGS_router_probe_interval", 0.25,
    "serving router: seconds between /healthz probes of each registered "
    "replica (drives live/ready/draining/dead tracking and load gauges)",
)
define_flag(
    "FLAGS_router_probe_timeout", 2.0,
    "serving router: per-probe HTTP timeout (s); a timed-out probe counts "
    "as a replica failure toward the circuit breaker",
)
define_flag(
    "FLAGS_router_max_retries", 3,
    "serving router: retry budget per request — connect failures, 503s, and "
    "retriable 504s fail over to another replica with jittered exponential "
    "backoff up to this many extra attempts (0 disables failover)",
)
define_flag(
    "FLAGS_router_retry_backoff", 0.05,
    "serving router: initial retry delay (s), doubled per attempt with "
    "+/-50% jitter; always clamped by the request's remaining deadline",
)
define_flag(
    "FLAGS_router_breaker_threshold", 3,
    "serving router: consecutive replica failures that trip its circuit "
    "breaker open (closed -> open -> half-open probe -> closed)",
)
define_flag(
    "FLAGS_router_breaker_cooldown", 1.0,
    "serving router: seconds an open circuit breaker waits before letting "
    "ONE half-open trial request through; success closes it, failure "
    "re-opens for another cooldown",
)
define_flag(
    "FLAGS_router_max_inflight", 64,
    "serving router: bounded admission — requests in flight through the "
    "router beyond this are shed with 503 + Retry-After from the healthiest "
    "replica's drain estimate (brownout)",
)
define_flag(
    "FLAGS_router_hedge_s", 0.0,
    "serving router: hedged dispatch delay (s) — a zero-token request still "
    "unanswered after this long is duplicated onto a second replica and the "
    "first completed response wins (pure generation makes the duplicate "
    "safe).  0 disables hedging.",
)
define_flag(
    "FLAGS_router_idem_ttl", 300.0,
    "crash-proof front door: seconds a completed response stays cached "
    "against its X-Idempotency-Key (router AND serve-side dedupe).  Within "
    "the TTL a resubmitted key replays the stored bytes instead of "
    "generating again; an in-flight resubmit joins the live request",
)
define_flag(
    "FLAGS_router_journal_segment_records", 1024,
    "control-plane journal: records per append-only segment file before "
    "rotating to a new one (checksummed lines, atomic-rename compaction; "
    "see serving/journal.py)",
)
define_flag(
    "FLAGS_router_takeover_timeout", 2.0,
    "router standby: seconds the primary's heartbeat seq may sit still "
    "(on the STANDBY's own clock — no cross-process clock comparison) "
    "before the standby declares it dead and takes over",
)
define_flag(
    "FLAGS_router_retry_after_jitter", 0.25,
    "serving router: +/- fractional jitter applied to Retry-After values "
    "emitted on sheds (brownout, no-replica, deadline-infeasible) so "
    "simultaneous 503s during takeover don't resynchronize clients into a "
    "thundering herd at the successor.  0 disables jitter",
)
define_flag(
    "FLAGS_autoscale_min_replicas", 1,
    "serving autoscaler: floor of the replica band — scale-down never "
    "drains below this many ready replicas",
)
define_flag(
    "FLAGS_autoscale_max_replicas", 4,
    "serving autoscaler: ceiling of the replica band — scale-up never "
    "spawns beyond this many managed replicas",
)
define_flag(
    "FLAGS_autoscale_interval", 0.5,
    "serving autoscaler: seconds between control-loop ticks (each tick "
    "reads every replica's probe snapshot and decides up/down/hold)",
)
define_flag(
    "FLAGS_autoscale_up_ticks", 2,
    "serving autoscaler hysteresis: consecutive pressured ticks required "
    "before a scale-up fires (one noisy probe must not spawn a replica)",
)
define_flag(
    "FLAGS_autoscale_down_ticks", 6,
    "serving autoscaler hysteresis: consecutive idle ticks required before "
    "a scale-down fires (asymmetric on purpose: scaling up is cheap to "
    "undo, draining a warm replica is not)",
)
define_flag(
    "FLAGS_autoscale_up_cooldown", 2.0,
    "serving autoscaler: seconds after ANY scaling action before another "
    "scale-UP may fire (lets the new replica's probes land before the "
    "loop judges the fleet under-provisioned again)",
)
define_flag(
    "FLAGS_autoscale_down_cooldown", 10.0,
    "serving autoscaler: seconds after ANY scaling action before a "
    "scale-DOWN may fire (longer than up: flapping capacity away during a "
    "burst lull re-queues real work)",
)
define_flag(
    "FLAGS_autoscale_up_drain_s", 0.5,
    "serving autoscaler pressure signal: the fleet's BEST (minimum) "
    "queue-drain estimate above this many seconds counts as a pressured "
    "tick — every replica already owes this much wall time",
)
define_flag(
    "FLAGS_autoscale_up_queue_depth", 4.0,
    "serving autoscaler pressure signal: mean queued requests per ready "
    "replica above this counts as a pressured tick",
)
define_flag(
    "FLAGS_autoscale_up_miss_rate", 0.05,
    "serving autoscaler pressure signal: any replica's deadline-miss-rate "
    "EWMA above this counts as a pressured tick (the SLO input)",
)
define_flag(
    "FLAGS_autoscale_min_page_free", 0.05,
    "serving autoscaler pressure signal: any replica's KV page-pool free "
    "fraction below this counts as a pressured tick (arena exhaustion "
    "rejects work the queue gauges cannot see)",
)
define_flag(
    "FLAGS_autoscale_down_drain_s", 0.05,
    "serving autoscaler idle signal: a tick is idle only when every ready "
    "replica's drain estimate is below this, no queue holds work, and the "
    "fleet is above the min band",
)
define_flag(
    "FLAGS_autoscale_tp_max", 1,
    "serving autoscaler: cap on the --tp degree chosen for a spawned "
    "replica (the controller picks the largest power of two that fits the "
    "unclaimed devices, clamped here; 1 = always single-device replicas)",
)
define_flag(
    "FLAGS_autoscale_down_idle_tokens_s", 0.0,
    "serving autoscaler cost signal: a scale-down additionally requires at "
    "least this much reclaimable idle decode capacity (tokens/s summed "
    "over idle ready replicas) — down-scaling optimizes $/token, not just "
    "emptiness.  0 keeps the pure-emptiness behavior",
)
define_flag(
    "FLAGS_debug_sanitize", False,
    "runtime trace/sync sanitizer (paddle_tpu.analysis.sanitizer): count "
    "every fresh trace, eager-cache miss, and device->host sync; inside a "
    "declared steady-state region (serving scheduler after warmup, the "
    "in-flight ring) any unexpected one is attributed to its user-level "
    "source line, surfaced in profiler.summary(), and raised as a hard "
    "error by the test suite's sanitize fixture",
)
define_flag(
    "FLAGS_trace",
    os.environ.get("PADDLE_TRACE", "") not in ("", "0", "false"),
    "host-side request tracing (paddle_tpu.obs): record per-stage spans "
    "(router.admit, replica.forward, serve.handle, engine.queue/prefill/"
    "decode/fetch, fit.step/window) into a bounded in-memory buffer, "
    "exported on GET /trace/<id> and as Chrome-trace JSON.  Pure host-side "
    "bookkeeping — no recompiles, no device syncs; off by default",
)
define_flag(
    "FLAGS_obs_buffer_events", 4096,
    "capacity of the obs span buffer and the flight-recorder event ring "
    "(paddle_tpu.obs); oldest entries are evicted first",
)


# ---------------------------------------------------------------------------
# Persistent compilation cache (tentpole of the compile-once cold start):
# every XLA compile — eager op executables, @to_static train steps, the
# inference Predictor — goes through jax's disk cache when a dir is set, so
# a (program, topology, version) pays its compile bill once per machine,
# not once per process.  The AOT snapshot tier (jit/cache.py) sits above
# this and additionally skips trace+lower.
# ---------------------------------------------------------------------------

_compile_cache_stats = {"disk_hits": 0, "requests": 0}
_cc_listener_installed = False


def _install_cc_listener():
    """Count jax's persistent-cache traffic: requests == compile calls that
    consulted the disk cache; disk_hits == loads that skipped XLA entirely.
    requests - disk_hits is therefore the fresh-XLA-compile count."""
    global _cc_listener_installed
    if _cc_listener_installed:
        return
    try:
        from jax._src import monitoring as _mon
    except ImportError:  # jax moved the module; stats stay zero
        return

    def _listener(event, **kw):
        if event == "/jax/compilation_cache/cache_hits":
            _compile_cache_stats["disk_hits"] += 1
        elif event == "/jax/compilation_cache/compile_requests_use_cache":
            _compile_cache_stats["requests"] += 1

    _mon.register_event_listener(_listener)
    _cc_listener_installed = True


def compile_cache_stats():
    d = _flags["FLAGS_compile_cache_dir"]
    out = dict(_compile_cache_stats)
    out["dir"] = d
    out["misses"] = out["requests"] - out["disk_hits"]
    entries = 0
    size = 0
    if d:
        try:
            for name in os.listdir(d):
                if name.endswith("-cache"):
                    entries += 1
                    try:
                        size += os.path.getsize(os.path.join(d, name))
                    except OSError:
                        pass
        except OSError:
            pass
    out["entries"] = entries
    out["bytes"] = size
    return out


def setup_compile_cache(path=None):
    """Point jax's persistent compilation cache at FLAGS_compile_cache_dir
    (or `path`, which also updates the flag).  Idempotent; re-invoked by
    set_flags when the flag changes.  Empty dir disables the disk cache."""
    if path is not None:
        _flags["FLAGS_compile_cache_dir"] = str(path)
    d = _flags["FLAGS_compile_cache_dir"]
    if not d:
        try:
            jax.config.update("jax_compilation_cache_dir", None)
        except (AttributeError, ValueError):
            pass
        return None
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    # cache every executable: the default thresholds skip small/fast
    # compiles, but cold-start latency is exactly the sum of those
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    _install_cc_listener()
    return d


_install_cc_listener()
setup_compile_cache()
