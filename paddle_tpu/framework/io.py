"""paddle.save / paddle.load (reference: python/paddle/framework/io.py).

Serialization: state dicts of Tensors → pickled dict of numpy arrays.  The
.pdparams/.pdopt naming conventions of the reference are honored.
"""

from __future__ import annotations

import os
import pickle

import numpy as np


def _to_serializable(obj):
    from ..tensor import Tensor

    if isinstance(obj, Tensor):
        arr = obj.numpy()
        if arr.dtype.name == "bfloat16":
            import jax.numpy as jnp
            return {"__bf16__": np.asarray(arr, dtype=np.float32)}
        return arr
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_serializable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_serializable(obj):
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__bf16__"}:
            import jax.numpy as jnp
            from ..tensor import Tensor
            return Tensor(jnp.asarray(obj["__bf16__"], dtype=jnp.bfloat16))
        return {k: _from_serializable(v) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        from ..tensor import Tensor
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        t = [_from_serializable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_serializable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        data = pickle.load(f)
    return _from_serializable(data)
