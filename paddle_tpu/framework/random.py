"""Seeded RNG state (reference: paddle/phi/core/generator.cc, paddle.seed).

TPU-native design: the generator owns a JAX PRNG key held in a Tensor so the
jit step-compiler's state-capture treats randomness as threaded state — each
compiled step consumes and advances the key functionally (no baked-in
constants), while eager mode simply splits the key per call.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Generator:
    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._key_tensor = None  # lazily created Tensor holding the PRNG key

    def _ensure(self):
        if self._key_tensor is None:
            from ..tensor import Tensor
            from . import core as _core

            with jax.ensure_compile_time_eval():
                self._key_tensor = Tensor(
                    jax.random.key_data(jax.random.PRNGKey(self._seed)),
                    stop_gradient=True,
                )
            _core.unmark_born(self._key_tensor)
        return self._key_tensor

    def manual_seed(self, seed: int):
        from ..tensor import Tensor

        self._seed = int(seed)
        self._key_tensor = Tensor(
            jax.random.key_data(jax.random.PRNGKey(self._seed)), stop_gradient=True
        )
        return self

    @property
    def initial_seed(self):
        return self._seed

    def next_key(self):
        """Split the state key; returns a fresh PRNG key (wrapped, typed)."""
        holder = self._ensure()
        raw = holder._data  # trace-aware read
        key = jax.random.wrap_key_data(raw)
        new_key, sub = jax.random.split(key)
        holder._data = jax.random.key_data(new_key)  # trace-aware write
        return sub

    def get_state(self):
        return self._ensure()._data

    def set_state(self, state):
        self._ensure()._data = jnp.asarray(state)


default_generator = Generator(0)


def seed(value: int):
    """paddle.seed — reset the global generator."""
    default_generator.manual_seed(int(value))
    return default_generator


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state):
    default_generator.set_state(state)
