"""Host-side bookkeeping for the block-paged KV cache (ISSUE 7).

The device side is dumb on purpose: per layer, one `[num_pages, page_size,
kv_heads, head_dim]` K/V arena plus per-slot page tables carried as traced
DATA through the compiled decode/prefill steps (engine.py).  Everything that
decides WHICH page holds WHICH tokens lives here, on the host, where it can
be mutated without recompiles:

- `PagePool` — refcounted free-list allocator over page ids.  Page 0 is a
  permanent scratch page: inactive slots' table rows are all-zero and every
  masked/out-of-range scatter is redirected to it, so garbage writes can
  never land in a page another sequence attends.
- `PrefixCache` — a token-chain index over COMMITTED prompt pages.  Full
  pages chain by `(parent_key, page_tokens)`; a partially filled last page
  is stored as a tail under its parent.  A new request walks the chain,
  maps every matched full page read-only (incref), and copy-on-writes the
  matched tail (the only shared page it would ever append into).  Entries
  are evicted LRU, leaves first, only when the allocator runs dry — the
  cache is a use for pages that would otherwise sit on the free list.

Sharing safety contract (relied on by the engine and the COW tests):

- readers of a cached page trust only rows < the entry's committed row
  count; everything beyond is masked by position, so the OWNER may keep
  appending into its own committed tail without invalidating readers;
- a reader never writes a shared page: full-page matches are read-only by
  construction (its own rows start after them) and the tail match is copied
  into a fresh page at admission, before any token lands.

Speculative verify (ISSUE 11) widens the decode write from one row to a
`[pos, pos+k]` window per slot.  The same scatter contract covers it: every
window row whose page-table entry is unmapped (table value 0) or beyond the
table redirects to scratch page 0, so REJECTED draft positions need no
rollback — their KV rows either landed in scratch or sit past the slot's
advanced `pos`, where the next verify window overwrites them before any
query can attend them (attention masks j <= pos+i).  `spec_write_pages`
below is the host-side mirror of that arithmetic, used by the engine's
debug-invariants check.
"""

from __future__ import annotations

import base64

import numpy as np


def spec_write_pages(pos, width, page_size, mapped_entries):
    """Page-table entries a verify window `[pos, pos+width)` writes through.

    Returns `(in_table, overrun)`: sorted entry indices that fall inside the
    slot's mapped table prefix (`entry < mapped_entries`) and those beyond it.
    Overrun entries MUST scatter to scratch page 0 on device — the engine's
    draft-budget clamp (`min(k, remaining-1)`) keeps every COMMITTED row in
    the mapped prefix, so a non-empty overrun set is only ever rejected-draft
    territory.  Pure host arithmetic; no device state."""
    pos, width, ps = int(pos), int(width), int(page_size)
    if width <= 0:
        return [], []
    entries = sorted({(pos + i) // ps for i in range(width)})
    in_table = [e for e in entries if e < mapped_entries]
    overrun = [e for e in entries if e >= mapped_entries]
    return in_table, overrun


# Quantized KV serving (ISSUE 18): 'int8' stores K/V pages as int8 with
# per-token-row, per-kv-head float32 scales kept in a parallel scale arena
# `[num_pages, page_size, kv_heads, 1]` (one per K and one per V per layer).
# Scale rows are written by the SAME scatters that write the quantized page
# rows and are addressed by the SAME page tables, so every piece of host
# bookkeeping in this module — refcounts, COW, prefix chains — covers them
# with zero extra state: holding a page holds its scale rows.
KV_QUANT_MODES = ("none", "int8")


class QuantConfigError(ValueError):
    """Raised at engine CONSTRUCTION time for an invalid KV-quantization
    configuration (unknown mode, quantized arena on a dense engine), so the
    operator sees a typed, actionable error instead of a mid-traffic shape
    or dtype mismatch inside a compiled step — the same contract as
    distributed.sharding.ShardingError (ISSUE 14)."""


def validate_kv_quant(mode, paged=True):
    """Typed validation of a kv_quant mode string (QuantConfigError on
    violation); returns the normalized mode.  Quantization requires the
    paged engine — the dense slot pool has no scale-arena plumbing and is
    kept as the full-precision bit-identity oracle."""
    mode = "none" if mode is None else str(mode).strip().lower()
    if mode not in KV_QUANT_MODES:
        raise QuantConfigError(
            f"kv_quant must be one of {'|'.join(KV_QUANT_MODES)}, got {mode!r}"
        )
    if mode != "none" and not paged:
        raise QuantConfigError(
            f"kv_quant={mode!r} requires the paged engine (paged=True): the "
            "dense slot pool stays full-precision as the bit-identity oracle"
        )
    return mode


def kv_page_bytes(page_size, kv_heads, head_dim, dtype_bytes, quant="none"):
    """HBM bytes ONE layer's K+V storage spends per page.  Under 'int8'
    every K/V element costs 1 byte plus a 4-byte float32 scale per
    (token row, kv head) — the scale arena's trailing unit dim.  This is
    the byte math behind FLAGS_serve_kv_pool_pages auto-sizing: the int8
    pool gets `head_dim*dtype_bytes / (head_dim + 4)` times the pages the
    same budget buys at full precision (~1.94x at bf16 head_dim=128)."""
    if validate_kv_quant(quant) == "int8":
        return 2 * int(page_size) * int(kv_heads) * (int(head_dim) + 4)
    return 2 * int(page_size) * int(kv_heads) * int(head_dim) * int(dtype_bytes)


def check_scale_arenas(arenas, num_pages, page_size):
    """Debug-invariants audit of the scale arenas (ISSUE 18): every int8
    layer arena must carry k_scale/v_scale buffers congruent with the K/V
    arena — same leading page count (the tables index both), same
    [page_size, kv_heads] row geometry, trailing unit dim, float32 — and a
    'none' arena must carry none.  The pool's refcounts need no separate
    scale accounting precisely BECAUSE of this congruence: page p's scale
    rows live and die with page p.  Raises AssertionError on violation."""
    for i, a in enumerate(arenas):
        quant = getattr(a, "quant", "none")
        ks, vs = getattr(a, "k_scale", None), getattr(a, "v_scale", None)
        if quant != "int8":
            if ks is not None or vs is not None:
                raise AssertionError(
                    f"scale invariant: layer {i} arena is quant={quant!r} "
                    "but carries scale buffers"
                )
            continue
        kvh = int(a.k.shape[2])
        want = (int(num_pages), int(page_size), kvh, 1)
        for name, t in (("k_scale", ks), ("v_scale", vs)):
            if t is None:
                raise AssertionError(
                    f"scale invariant: layer {i} int8 arena missing {name}"
                )
            if tuple(int(d) for d in t.shape) != want:
                raise AssertionError(
                    f"scale invariant: layer {i} {name} shape "
                    f"{tuple(t.shape)} != {want}"
                )
            if "float32" not in str(t.dtype):
                raise AssertionError(
                    f"scale invariant: layer {i} {name} dtype {t.dtype} "
                    "is not float32"
                )


# Canonical tensor-parallel layout of every KV cache buffer (ISSUE 14):
# paged arenas are [num_pages, page_size, kv_heads, head_dim] and dense slot
# pools are [slots, max_len, kv_heads, head_dim] — both split the KV HEADS
# axis (dim 2) over the 'mp' mesh axis, so each device stores and streams
# only its local heads' rows.  Page identity, table entries, and every piece
# of host-side bookkeeping in this module stay device-count-agnostic: a page
# is the SAME page on every shard, just narrower.
KV_TP_AXIS = 2


def shard_kv_for_tp(cache):
    """Place a KV cache's k/v buffers on the installed serving mesh: kv
    heads (dim 2) split over 'mp' (see KV_TP_AXIS) and — for paged arenas
    under context parallelism (ISSUE 20) — the PAGE axis (dim 0) block-split
    over 'cp', so shard s physically holds pages [s*per_shard,
    (s+1)*per_shard) and the cp decode kernel streams only local pages.
    No-op without a mesh, so the engine calls it unconditionally; returns
    the cache for chaining."""
    from jax.sharding import PartitionSpec as P

    from ..distributed import mesh as _mesh

    cp = _mesh.axis_size("cp")
    if _mesh.get_mesh() is None or (_mesh.axis_size("mp") <= 1 and cp <= 1):
        return cache
    # dim 0 is pages only for paged arenas (PagedKVCache carries page_size);
    # a dense slot pool's dim 0 is SLOTS — never cp-sharded
    page_axis = "cp" if (cp > 1 and hasattr(cache, "page_size")) else None
    mp_axis = "mp" if _mesh.axis_size("mp") > 1 else None
    spec = P(page_axis, None, mp_axis, None)
    _mesh.shard_tensor_(cache.k, spec)
    _mesh.shard_tensor_(cache.v, spec)
    # int8 arenas (ISSUE 18): scale buffers share the [pages, page_size,
    # kv_heads, 1] layout, so the same kv-heads sharding applies — each
    # device holds exactly its local heads' scale rows
    for name in ("k_scale", "v_scale"):
        t = getattr(cache, name, None)
        if t is not None:
            _mesh.shard_tensor_(t, spec)
    return cache


def check_table_bounds(table, num_pages):
    """Every page-table entry must name a real arena page: the fused paged
    Pallas kernel indexes the arena by the RAW table value inside its
    BlockSpec index maps (no clamp — a clamp would hide corruption as a
    silent wrong-page read), so an out-of-range entry is device-undefined
    behavior, not just a wrong answer.  Raises AssertionError on violation.
    Pure host arithmetic; `table` is the host mirror ([..., P] int array)."""
    t = np.asarray(table)
    if t.size == 0:
        return
    lo, hi = int(t.min()), int(t.max())
    if lo < 0 or hi >= int(num_pages):
        bad = np.argwhere((t < 0) | (t >= int(num_pages)))
        raise AssertionError(
            f"page table entries out of arena bounds [0, {int(num_pages)}): "
            f"min={lo}, max={hi}, first bad index={bad[0].tolist()}"
        )


# ---------------------------------------------------------------------------
# Disaggregated prefill/decode handoff wire format (ISSUE 19).  A prefill
# worker ships the COMMITTED prompt rows of every layer's K/V arena to a
# decode worker as ROW payloads — `[L, kv_heads, head_dim]` per layer, raw
# little-endian bytes, base64 for the JSON hop — deliberately page-size
# agnostic so the two sides may run different page geometries.  Under
# kv_quant='int8' the rows ship AS STORED (int8 elements + the float32
# per-row/per-head scale rows from the parallel scale arena), so handoff
# bytes get the same ~2x saving the arena gets and the decode side imports
# bit-identical quantized rows: no re-quantization, no drift.
# ---------------------------------------------------------------------------

HANDOFF_VERSION = 1


class HandoffFormatError(ValueError):
    """Raised when a handoff payload cannot be imported by the receiving
    decode engine — wrong version, mismatched quant mode / KV geometry /
    layer count, or corrupt row bytes.  Typed so the serving layer can map
    it to a 4xx instead of crashing a compiled step (same contract as
    QuantConfigError above)."""


def _np_dtype(name):
    """np.dtype for a cache dtype name, covering the ml_dtypes extension
    types (bfloat16 etc.) that plain numpy doesn't parse."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _b64(arr):
    return base64.b64encode(np.ascontiguousarray(arr).tobytes()).decode("ascii")


def _unb64(s, dtype, shape, what):
    buf = base64.b64decode(s.encode("ascii"))
    want = int(np.prod(shape)) * dtype.itemsize
    if len(buf) != want:
        raise HandoffFormatError(
            f"handoff {what}: {len(buf)} bytes, expected {want} for "
            f"shape {tuple(shape)} dtype {dtype}"
        )
    return np.frombuffer(buf, dtype=dtype).reshape(shape).copy()


def serialize_kv_handoff(layers, prompt_len, quant, dtype_name):
    """Pack per-layer exported prompt rows into a JSON-safe handoff payload.

    `layers` is a list (one per model layer) of dicts with 'k'/'v' arrays of
    shape [L, kv_heads, head_dim] (int8 under quant='int8', else the cache
    dtype) plus 'k_scale'/'v_scale' [L, kv_heads, 1] float32 when quantized.
    Returns the payload dict; its 'payload_bytes' field counts the RAW row
    bytes (pre-base64) — the number the bench and paddle_disagg_* metrics
    report as handoff traffic."""
    if not layers:
        raise HandoffFormatError("handoff payload needs >= 1 layer")
    L = int(prompt_len)
    kvh, hd = int(layers[0]["k"].shape[1]), int(layers[0]["k"].shape[2])
    quant = validate_kv_quant(quant)
    raw = 0
    packed = []
    for ly in layers:
        rec = {"k": _b64(ly["k"]), "v": _b64(ly["v"])}
        raw += ly["k"].nbytes + ly["v"].nbytes
        if quant == "int8":
            rec["k_scale"] = _b64(ly["k_scale"])
            rec["v_scale"] = _b64(ly["v_scale"])
            raw += ly["k_scale"].nbytes + ly["v_scale"].nbytes
        packed.append(rec)
    return {
        "version": HANDOFF_VERSION,
        "prompt_len": L,
        "quant": quant,
        "kv_heads": kvh,
        "head_dim": hd,
        "n_layers": len(layers),
        "dtype": str(dtype_name),
        "payload_bytes": int(raw),
        "layers": packed,
    }


def deserialize_kv_handoff(payload, quant, kv_heads, head_dim, n_layers, dtype_name):
    """Unpack + validate a handoff payload against the RECEIVING engine's
    arena geometry.  Returns (layers, prompt_len) where `layers` mirrors the
    serialize_kv_handoff input layout.  Every mismatch is a typed
    HandoffFormatError — the decode engine must never feed foreign-geometry
    rows into its compiled import scatter."""
    if not isinstance(payload, dict):
        raise HandoffFormatError(f"handoff payload is {type(payload).__name__}, not a dict")
    if int(payload.get("version", -1)) != HANDOFF_VERSION:
        raise HandoffFormatError(
            f"handoff version {payload.get('version')!r} != {HANDOFF_VERSION}"
        )
    quant = validate_kv_quant(quant)
    for field, want in (
        ("quant", quant),
        ("kv_heads", int(kv_heads)),
        ("head_dim", int(head_dim)),
        ("n_layers", int(n_layers)),
        ("dtype", str(dtype_name)),
    ):
        got = payload.get(field)
        got = type(want)(got) if got is not None else got
        if got != want:
            raise HandoffFormatError(
                f"handoff {field} mismatch: payload has {got!r}, "
                f"this engine expects {want!r}"
            )
    L = int(payload.get("prompt_len", 0))
    if L <= 0:
        raise HandoffFormatError(f"handoff prompt_len {L} must be positive")
    rows = payload.get("layers")
    if not isinstance(rows, list) or len(rows) != int(n_layers):
        raise HandoffFormatError(
            f"handoff carries {len(rows) if isinstance(rows, list) else '?'} "
            f"layer records, expected {int(n_layers)}"
        )
    elem = np.dtype(np.int8) if quant == "int8" else _np_dtype(dtype_name)
    kvh, hd = int(kv_heads), int(head_dim)
    out = []
    for i, rec in enumerate(rows):
        ly = {
            "k": _unb64(rec["k"], elem, (L, kvh, hd), f"layer {i} k"),
            "v": _unb64(rec["v"], elem, (L, kvh, hd), f"layer {i} v"),
        }
        if quant == "int8":
            f32 = np.dtype(np.float32)
            ly["k_scale"] = _unb64(rec["k_scale"], f32, (L, kvh, 1), f"layer {i} k_scale")
            ly["v_scale"] = _unb64(rec["v_scale"], f32, (L, kvh, 1), f"layer {i} v_scale")
        out.append(ly)
    return out, L


class PagePool:
    """Refcounted page allocator.  Page 0 is scratch: pinned, never handed
    out, the target of every redirected garbage write.

    Context parallelism (ISSUE 20) block-shards the arena's page axis over
    the 'cp' mesh axis, so the pool optionally partitions its id space into
    `shards` equal contiguous ranges — shard s owns [s*per_shard,
    (s+1)*per_shard) and its FIRST page (s*per_shard) is that device's local
    scratch, pinned like page 0.  Sequence page k must be allocated from
    shard k % cp (the round-robin layout the cp decode kernel assumes), so
    `alloc` takes the owning shard.  shards=1 is the exact legacy pool."""

    def __init__(self, num_pages, shards=1):
        shards = int(shards) if shards else 1
        if shards < 1:
            raise ValueError(f"page pool shards must be >= 1, got {shards}")
        if num_pages % shards:
            raise ValueError(
                f"page pool size {num_pages} must divide evenly into "
                f"{shards} shards"
            )
        if num_pages < 2 * shards:
            raise ValueError(
                "page pool needs >= 2 pages per shard (1 scratch + 1 usable)"
            )
        self.num_pages = int(num_pages)
        self.shards = shards
        self.per_shard = self.num_pages // shards
        self.scratch_pages = tuple(s * self.per_shard for s in range(shards))
        self.refs = np.zeros(self.num_pages, np.int64)
        for p in self.scratch_pages:
            self.refs[p] = 1  # scratch, pinned forever
        self._free_by_shard = [
            list(range(s * self.per_shard + 1, (s + 1) * self.per_shard))
            for s in range(shards)
        ]

    @property
    def _free(self):
        """Flat read-only view of every free page id (audits and tests);
        allocation goes through the per-shard lists."""
        return [p for lst in self._free_by_shard for p in lst]

    @property
    def usable_pages(self):
        return self.num_pages - self.shards

    def shard_of(self, page):
        return int(page) // self.per_shard

    def is_scratch(self, page):
        return int(page) % self.per_shard == 0

    def free_count(self, shard=None):
        if shard is None:
            return sum(len(lst) for lst in self._free_by_shard)
        return len(self._free_by_shard[shard])

    def used_count(self):
        return self.usable_pages - self.free_count()

    def alloc(self, shard=0):
        """One page at refcount 1 from `shard`'s range; the caller must have
        checked free_count (the engine's admission math guarantees it never
        runs dry)."""
        if not self._free_by_shard[shard]:
            raise RuntimeError(
                f"page pool shard {shard} exhausted — admission reservations "
                "should have prevented this allocation (accounting bug)"
            )
        p = self._free_by_shard[shard].pop(0)
        assert self.refs[p] == 0, f"free-list page {p} had refcount {self.refs[p]}"
        self.refs[p] = 1
        return p

    def incref(self, page):
        assert not self.is_scratch(page), "scratch page is never mapped"
        assert self.refs[page] > 0, f"incref on dead page {page}"
        self.refs[page] += 1

    def decref(self, page):
        """Drop one reference; a page hitting 0 returns to its shard's free
        list."""
        assert not self.is_scratch(page), "scratch page is never released"
        assert self.refs[page] > 0, f"decref on dead page {page}"
        self.refs[page] -= 1
        if self.refs[page] == 0:
            self._free_by_shard[self.shard_of(page)].append(page)
            return True
        return False


class _Entry:
    __slots__ = ("key", "parent_key", "page", "rows", "children", "last_used",
                 "tokens", "pinned")

    def __init__(self, key, parent_key, page, rows, tokens):
        self.key = key
        self.parent_key = parent_key
        self.page = int(page)
        self.rows = int(rows)  # committed rows; readers trust only j < rows
        self.children = 0
        self.last_used = 0
        self.tokens = tokens  # the page's committed token ids (tuple)
        self.pinned = 0  # session holds (ISSUE 20); > 0 => never evictable


class PrefixCache:
    """Token-chain index over committed prompt pages.

    Full pages are keyed `(parent_key, page_tokens)` so equal prefixes
    converge on the same chain regardless of which request committed them;
    partial last pages are stored as tails under their parent and matched by
    longest common prefix.  Eviction is LRU over childless entries only — a
    parent outlives its children, so no chain ever dangles.

    Chains are rooted per ADAPTER (ISSUE 12): a prompt prefilled under LoRA
    adapter A produced K/V that embed A's deltas, so a request under adapter
    B (or the base model) must never COW-reuse those pages even for an
    identical token chain.  `lookup`/`commit` take the request's STABLE
    registry adapter id (0 = base) and walk from a per-adapter root — equal
    prompts still share within an adapter, never across.
    """

    _ROOT = ()

    def _root(self, adapter):
        """Chain root for one adapter id.  The sentinel tuple can't collide
        with a full-page key (whose first element is itself a key, never the
        marker string) and is truthy, which `_remove`'s parent walk already
        handles (no full entry is keyed by it, so the parent lookup misses
        cleanly)."""
        return self._ROOT if not adapter else ("__lora__", int(adapter))

    def __init__(self, page_size):
        self.page_size = int(page_size)
        self._full = {}   # key -> _Entry (rows == page_size)
        self._tails = {}  # parent_key -> [ _Entry ] (rows < page_size)
        self._clock = 0

    def __len__(self):
        return len(self._full) + sum(len(v) for v in self._tails.values())

    def entries(self):
        for e in self._full.values():
            yield e
        for tails in self._tails.values():
            yield from tails

    def _tick(self, entry):
        self._clock += 1
        entry.last_used = self._clock

    def lookup(self, prompt, adapter=0):
        """Longest cached prefix of `prompt` (np.int32 [L]) committed under
        the same `adapter` id, capped at L-1 so at least one suffix token
        remains to prefill and sample from.  Returns (match_len, full_pages,
        tail_page, tail_rows): `full_pages` are read-only mappable as-is,
        the tail page (if any) must be copy-on-written before the reader
        appends.  Bumps LRU on the matched chain; refcounts are the
        caller's job (it holds the pool)."""
        ps = self.page_size
        L = int(prompt.size)
        toks = prompt.tolist()
        key = self._root(adapter)
        full_pages = []
        matched = []
        i = 0
        while i + ps <= L - 1:  # a full-page match must leave >= 1 suffix token
            child = self._full.get((key, tuple(toks[i : i + ps])))
            if child is None:
                break
            full_pages.append(child.page)
            matched.append(child)
            key = child.key
            i += ps
        tail_page, tail_rows = None, 0
        best = None
        for e in self._tails.get(key, ()):
            lcp = 0
            for a, b in zip(e.tokens, toks[i : L - 1]):  # cap total match at L-1
                if a != b:
                    break
                lcp += 1
            if lcp > tail_rows:
                tail_rows, tail_page, best = lcp, e.page, e
        if best is not None:
            matched.append(best)
        for e in matched:
            self._tick(e)
        return i + tail_rows, full_pages, tail_page, tail_rows

    def commit(self, prompt, pages, pool, adapter=0):
        """Insert-if-absent the prompt's pages after its prefill completed:
        one full-page entry per complete page, one tail for the remainder,
        chained under the committing request's `adapter` root.  New entries
        incref their page (the cache's own hold); pages whose chain position
        is already cached are left alone — the committer may have mapped
        that very entry's page at admission."""
        ps = self.page_size
        L = int(prompt.size)
        toks = prompt.tolist()
        key = self._root(adapter)
        inserted = 0
        for i in range(L // ps):
            ek = (key, tuple(toks[i * ps : (i + 1) * ps]))
            e = self._full.get(ek)
            if e is None:
                e = _Entry(ek, key, pages[i], ps, ek[1])
                self._full[ek] = e
                pool.incref(e.page)
                parent = self._full.get(key) if key is not self._ROOT else None
                if parent is not None:
                    parent.children += 1
                inserted += 1
            self._tick(e)
            key = e.key
        rows = L % ps
        if rows:
            tokens = tuple(toks[L - rows : L])
            tails = self._tails.setdefault(key, [])
            for e in tails:
                if e.tokens == tokens:
                    self._tick(e)
                    return inserted
            e = _Entry((key, tokens), key, pages[L // ps], rows, tokens)
            tails.append(e)
            pool.incref(e.page)
            parent = self._full.get(key) if key is not self._ROOT else None
            if parent is not None:
                parent.children += 1
            self._tick(e)
            inserted += 1
        return inserted

    def _remove(self, entry):
        if entry.rows == self.page_size:
            del self._full[entry.key]
            self._tails.pop(entry.key, None)  # only ever empty lists by now
        else:
            tails = self._tails.get(entry.parent_key, [])
            tails.remove(entry)
            if not tails:
                self._tails.pop(entry.parent_key, None)
        parent = self._full.get(entry.parent_key) if entry.parent_key else None
        if parent is not None:
            parent.children -= 1

    def evict_one(self, pool, shard=None):
        """Drop the LRU childless UNPINNED entry and release its page hold.
        Returns the evicted entry or None when nothing is evictable.  The
        freed page only reaches the free list if no live slot still maps
        it — eviction never invalidates a reader.

        Session-pinned entries (entry.pinned > 0, ISSUE 20) are never
        "childless-evictable": a session's committed chain must survive page
        pressure until the SESSION is evicted (SessionStore.evict_lru drops
        the pins first).  Under a sharded pool, `shard` restricts victims to
        entries whose page lives in that shard's range — evicting elsewhere
        cannot relieve that shard's pressure."""
        victim = None
        for e in self.entries():
            if e.pinned > 0:
                continue  # session hold — the session evicts first
            if shard is not None and pool.shard_of(e.page) != shard:
                continue
            if e.rows == self.page_size and (
                e.children > 0 or self._tails.get(e.key)
            ):
                continue  # a parent outlives its children
            if victim is None or e.last_used < victim.last_used:
                victim = e
        if victim is None:
            return None
        self._remove(victim)
        pool.decref(victim.page)
        return victim

    def chain(self, tokens, adapter=0):
        """The committed entry chain covering the longest cached prefix of
        `tokens` (np.int32 [L]) under `adapter` — full-page links plus an
        EXACT-match tail.  Unlike `lookup`, coverage may reach all L tokens
        (it walks what `commit` wrote, not what a new reader could reuse)
        and the LRU clock is NOT bumped.  Returns (entries, covered_tokens);
        the SessionStore pins exactly this chain."""
        ps = self.page_size
        toks = tokens.tolist() if hasattr(tokens, "tolist") else list(tokens)
        L = len(toks)
        key = self._root(adapter)
        out = []
        i = 0
        while i + ps <= L:
            e = self._full.get((key, tuple(toks[i : i + ps])))
            if e is None:
                break
            out.append(e)
            key = e.key
            i += ps
        covered = i
        rows = L - i
        if 0 < rows < ps:
            for e in self._tails.get(key, ()):
                if e.tokens == tuple(toks[i:L]):
                    out.append(e)
                    covered = L
                    break
        return out, covered

    def clear(self, pool):
        """Release every cache hold (engine shutdown / tests).  Session pins
        are dropped first — callers tearing down the cache tear down the
        sessions with it (SessionStore holds no page refs of its own)."""
        for e in self.entries():
            e.pinned = 0
        n = 0
        while self.evict_one(pool) is not None:
            n += 1
        return n


class SessionStore:
    """First-class multi-turn session KV (ISSUE 20).

    A session is a named, refcounted hold on the PrefixCache chain covering
    its committed conversation — prompt AND generated tokens of every turn
    so far.  `bind` walks the chain `PrefixCache.chain` returns for the
    committed sequence and bumps `entry.pinned` on each link (un-bumping the
    previous turn's chain), so under page pressure `evict_one` can never
    reclaim a live session's pages; the pool refcounts themselves stay the
    cache's — pinning adds no double accounting for the invariant audit to
    untangle.  Turn N+1's request then chunk-prefills ONLY the unshared
    suffix through the ordinary prefix-cache admission path, at true rope
    offsets, with zero new executables.

    Sessions are evicted LRU-whole (a half-pinned chain would be useless),
    either by capacity at bind time or explicitly by the engine's allocator
    when the prefix cache alone cannot relieve page pressure.  The store
    survives warm `restart()`/`fail_all()` for free: it references cache
    entries, and the warm paths keep pool + prefix cache intact."""

    def __init__(self, capacity=256):
        self.capacity = max(1, int(capacity))
        self._sessions = {}  # sid -> record dict
        self._clock = 0
        self.tokens_saved_total = 0  # prefill tokens served from pinned KV
        self.evictions = 0
        self.binds = 0

    def __len__(self):
        return len(self._sessions)

    def __contains__(self, sid):
        return sid in self._sessions

    def sessions(self):
        return list(self._sessions.values())

    def get(self, sid):
        return self._sessions.get(sid)

    def tokens(self, sid):
        s = self._sessions.get(sid)
        return None if s is None else s["tokens"]

    def touch(self, sid):
        s = self._sessions.get(sid)
        if s is not None:
            self._clock += 1
            s["last_used"] = self._clock
        return s

    def bind(self, sid, tokens, entries, adapter=0, tenant=""):
        """(Re)bind `sid` to the committed sequence `tokens` whose cache
        chain is `entries`: pin the new chain, then unpin the previous one
        (in that order, so shared links never transit refcount 0).  Returns
        the session ids evicted to stay within capacity."""
        self._clock += 1
        old = self._sessions.pop(sid, None)
        for e in entries:
            e.pinned += 1
        if old is not None:
            for e in old["entries"]:
                e.pinned -= 1
        self._sessions[sid] = {
            "sid": sid,
            "tokens": np.asarray(tokens, np.int32).copy(),
            "entries": list(entries),
            "adapter": int(adapter),
            "tenant": str(tenant or ""),
            "last_used": self._clock,
            "turns": (old["turns"] + 1) if old else 1,
        }
        self.binds += 1
        evicted = []
        while len(self._sessions) > self.capacity:
            v = self.evict_lru(exclude=sid)
            if v is None:
                break
            evicted.append(v)
        return evicted

    def release(self, sid):
        s = self._sessions.pop(sid, None)
        if s is None:
            return False
        for e in s["entries"]:
            e.pinned -= 1
        return True

    def evict_lru(self, exclude=None):
        """Unpin + drop the least-recently-used session (whole — a partially
        pinned chain serves nobody).  Returns its sid, or None."""
        victim = None
        for sid, s in self._sessions.items():
            if sid == exclude:
                continue
            if victim is None or s["last_used"] < victim["last_used"]:
                victim = s
        if victim is None:
            return None
        self.release(victim["sid"])
        self.evictions += 1
        return victim["sid"]

    def clear(self):
        for sid in list(self._sessions):
            self.release(sid)

    def pages_pinned(self):
        """Distinct cache entries (== pages) held by at least one session."""
        return len({id(e) for s in self._sessions.values() for e in s["entries"]})

    def stats(self):
        tenants = {s["tenant"] for s in self._sessions.values()}
        return {
            "sessions_resident": len(self._sessions),
            "session_tenants": len(tenants),
            "session_pages_pinned": self.pages_pinned(),
            "session_prefill_tokens_saved_total": int(self.tokens_saved_total),
            "session_evictions_total": int(self.evictions),
            "session_binds_total": int(self.binds),
        }

    def check(self, cache, pool):
        """FLAGS_serve_debug_invariants audit clause (ISSUE 20): every pin
        on a cache entry is explained by exactly the sessions holding it,
        every pinned entry is still IN the cache with a live page, and no
        session references an entry the cache no longer owns.  Raises
        AssertionError on violation."""
        want = {}
        for s in self._sessions.values():
            for e in s["entries"]:
                want[id(e)] = want.get(id(e), 0) + 1
        live = {id(e): e for e in cache.entries()}
        for s in self._sessions.values():
            for e in s["entries"]:
                if id(e) not in live:
                    raise AssertionError(
                        f"session invariant: session {s['sid']!r} pins page "
                        f"{e.page} whose cache entry was removed"
                    )
        for e in cache.entries():
            w = want.get(id(e), 0)
            if e.pinned != w:
                raise AssertionError(
                    f"session invariant: entry page {e.page} pinned="
                    f"{e.pinned} but {w} session hold(s) reference it"
                )
            if e.pinned > 0 and pool.refs[e.page] <= 0:
                raise AssertionError(
                    f"session invariant: pinned page {e.page} has refcount "
                    f"{int(pool.refs[e.page])}"
                )
