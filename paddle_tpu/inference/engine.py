"""Continuous-batching inference engine (reference capability: the inference
runtime's flash-decode serving path, SURVEY §2.1 L8 — scheduling layer).

The lock-step `GenerationPredictor` runs every request in a batch from first
token to last together: one long generation holds the whole batch hostage,
and a new request waits for the batch to drain.  This engine instead owns a
persistent SLOT POOL of `StaticKVCache` buffers (`[slots, max_len, kv_heads,
head_dim]` per layer) and runs ONE compiled decode step whatever the
occupancy: per-slot `pos` and `active` masks are DATA, never shapes, so
requests joining, finishing, and slots being recycled cause zero recompiles
after warmup.

New requests are prefilled through length-bucketed compiled prefill
executables — the prompt pads up to its bucket, attends to itself causally,
and its K/V land in the assigned pool slot (slot index is data too, so one
executable per bucket serves every slot).  Prefills interleave with in-flight
decode at step granularity; finished slots (EOS or max_new_tokens) are
recycled immediately.

Why padding garbage is safe: a prefill writes rows [0, bucket) of its slot,
rows [true_len, bucket) holding padding K/V.  Decode at position p first
overwrites row p, then attends rows j <= p only — every garbage row is
overwritten by the decode step that first brings it into the attended window.
Inactive slots decode with pos forced to 0; their row-0 write is scratch
because the next prefill into that slot always rewrites row 0.

Compiled-executable budget: len(prefill_buckets) + 1 (asserted by tests via
`compile_counts()`).  Both functions ride @to_static, so PR 3's persistent
compile cache and AOT snapshots apply per bucket: a restarted server binds
the previous process's executables without tracing.

Paged KV (ISSUE 7, default on via FLAGS_serve_paged_kv): instead of one
dense `[slots, max_len, ...]` buffer per layer, K/V live in a block-paged
ARENA `[num_pages, page_size, kv_heads, head_dim]` addressed through
per-slot page tables (`[slots, max_pages_per_seq]` int32) that ride the
compiled steps as DATA — join/finish/recycle still cause zero recompiles.
A request only occupies pages covering `prompt + max_new_tokens`, so the
same KV budget serves far more concurrent sequences than `slots * max_len`
dense rows.  A host-side `PrefixCache` indexes committed prompt pages:
a request sharing a cached prefix maps the shared full pages READ-ONLY
(refcounted), copy-on-writes only a partially filled shared page, and
prefills just the unshared suffix through a chunk-prefill executable
(rope offset and page table as data).  The compiled budget becomes
2 * len(buckets) + 2 (fresh + chunk per bucket, decode, page copy); the
`compile_counts()` contract keys are prefill/chunk_prefill/decode/copy.
Admission gates on pages: submit raises QueueFull when a request's worst
case page need exceeds the pool, and the scheduler defers admission (the
request stays at the head of the line) until free + cache-evictable pages
cover it.  Restart keeps the pool AND the prefix cache warm.

Serving fault domain (the serving mirror of the training fault domain):

- **Request lifecycle** — every submitted request resolves EXACTLY once:
  queued → prefilling → decoding → {eos, length, timeout, cancelled,
  restarted, error}.  `deadline_s` evicts an expired slot at step
  granularity (slot recycle, no recompile) and `submit` rejects requests
  whose deadline cannot beat the current queue-drain estimate; `cancel()`
  frees the slot the same way.
- **Watchdogged regions** — prefill dispatch, decode dispatch, and the
  host token fetch run under `fault.watchdog.arm` with deadline
  `FLAGS_serve_step_timeout_sec`; an overrun records a trip (it does NOT
  kill the process — serving restarts the ENGINE) that the
  `fault.EngineSupervisor` turns into a bounded warm restart.
- **Warm restart** — `restart()` abandons a wedged scheduler thread via a
  generation counter (the stale thread aborts at its next state touch),
  re-queues in-flight requests that emitted no tokens yet, fails the rest
  with the typed `EngineRestarted` error, and rebinds the SAME compiled
  executables and KV pool: 0 fresh compiles, asserted by the chaos drills.
  Reusing the pool un-scrubbed is safe by the padding-garbage invariant
  above.
- **Injectable faults** — `serve.prefill.hang` (blocks the prefill
  dispatch), `serve.decode.nan` (poisons ONE slot's logits with NaN as
  traced data for one step; only that request errors, co-batched requests
  are bit-identical to an unpoisoned run), `serve.loop.crash` (kills the
  scheduler thread) — armed via the usual `FLAGS_fault_inject` registry.

Speculative decoding (ISSUE 11, paged engines, FLAGS_serve_spec_k > 0):
decode is HBM-bandwidth-bound — one token per step leaves the FLOPs idle —
so the engine drafts k candidate tokens per greedy slot with a host-side
prompt-lookup `NgramDrafter` (no second model; spec.py) and the target
model verifies all k+1 positions in ONE compiled forward over the same
paged arena (`_verify_paged_body`, shaped [slots, k+1]).  Acceptance
length, proposed tokens, and per-slot draft validity are DATA, so the
compiled budget grows by exactly one executable (`compile_counts()` gains
`verify`) and join/finish/recycle still cause zero recompiles.  Greedy
equivalence is structural: draft i is accepted only while it equals the
model's own greedy continuation, so output is token-identical to the
plain engine whatever the drafter proposes — rejected-position KV writes
land on scratch (page-table redirect) or past the advanced `pos`, where
the next window overwrites them before anything attends them.  Sampled
(temp > 0) slots ride the same step at draft length 0, column 0 sampling
on the plain decode's key schedule.  The drain/admission EWMA consumes
observed tokens-per-step so Retry-After and DeadlineUnattainable stay
honest when steps emit >1 token.
"""

from __future__ import annotations

import contextlib
import itertools
import logging
import math
import queue
import threading
import time

import numpy as np

from ..analysis import sanitizer as _san
from ..fault import injection as _inj
from ..fault import watchdog as _wd
from ..framework import core as _fcore
from ..obs import flight as _flight
from ..obs import trace as _obs
from ..models.llama import (
    PagedDecodeView,
    PagedKVCache,
    PagedPrefillView,
    SlotView,
    StaticKVCache,
)
from ..tensor import Tensor
from .paging import (
    PagePool,
    PrefixCache,
    QuantConfigError,
    SessionStore,
    check_scale_arenas,
    check_table_bounds,
    kv_page_bytes,
    shard_kv_for_tp,
    spec_write_pages,
    validate_kv_quant,
)
from .spec import NgramDrafter

logger = logging.getLogger("paddle_tpu")

# deadline-miss-rate EWMA weight per terminal resolution: ~20-request
# memory, so one miss reads 0.05 and a sustained miss storm saturates
# toward 1.0 within a few dozen requests — fast enough for an autoscaler
# tick, long enough that one straggler does not flap the fleet
_MISS_EWMA_ALPHA = 0.05


class EngineUnavailable(RuntimeError):
    """The engine cannot take this request right now (queue full, draining,
    dead, or an unattainable deadline) — serve() maps this family to HTTP
    503 with a Retry-After derived from the queue-drain estimate."""

    def __init__(self, msg, retry_after_s=None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class QueueFull(EngineUnavailable):
    """Admission queue at capacity — submit() fails fast (serve() maps this
    to HTTP 503)."""


class DeadlineUnattainable(EngineUnavailable):
    """Deadline-aware admission: the request's deadline cannot beat the
    current queue-drain estimate, so admitting it would only burn a slot on
    work guaranteed to be evicted."""


class ContextOverflow(ValueError):
    """Typed 400 (ISSUE 20): the prompt (or prompt + requested generation)
    cannot fit this engine's context — raised at ADMISSION, before any page
    is reserved or allocated, so an over-length request costs nothing.
    Carries the capacity geometry (per-shard under cp) for the HTTP body."""

    def __init__(self, prompt_len, max_len, cp=1, pages_per_shard=0,
                 page_size=0):
        self.prompt_len = int(prompt_len)
        self.max_len = int(max_len)
        self.cp = int(cp)
        self.pages_per_shard = int(pages_per_shard)
        self.page_size = int(page_size)
        detail = f"prompt length {self.prompt_len} exceeds engine capacity: "
        detail += f"max_len={self.max_len}"
        if self.cp > 1:
            detail += (
                f" (cp={self.cp} shards x {self.pages_per_shard} pages x "
                f"{self.page_size} tokens/page per shard)"
            )
        super().__init__(detail)

    def body(self):
        """JSON-safe capacity record for the serving layer's 400 body."""
        out = {
            "prompt_len": self.prompt_len,
            "max_len": self.max_len,
            "cp": self.cp,
        }
        if self.pages_per_shard:
            out["pages_per_shard"] = self.pages_per_shard
            out["page_size"] = self.page_size
            out["tokens_per_shard"] = self.pages_per_shard * self.page_size
        return out


class DeadlineExceeded(TimeoutError):
    """The request's deadline expired mid-flight; its slot was evicted at
    step granularity (recycled, no recompile)."""

    def __init__(self, request_id, tokens_done, max_new_tokens, deadline_s):
        self.request_id = request_id
        self.tokens_done = tokens_done
        super().__init__(
            f"request {request_id} missed its {deadline_s}s deadline "
            f"({tokens_done}/{max_new_tokens} tokens generated)"
        )


class RequestCancelled(RuntimeError):
    """The request was cancelled via EngineRequest.cancel()."""

    def __init__(self, request_id, tokens_done):
        self.request_id = request_id
        self.tokens_done = tokens_done
        super().__init__(
            f"request {request_id} cancelled ({tokens_done} tokens generated)"
        )


class EngineRestarted(RuntimeError):
    """503-style typed error: the engine restarted (or died) while this
    request was in flight and its decode state was lost.  The request was
    NOT silently dropped — retry it."""

    def __init__(self, request_id, reason=""):
        self.request_id = request_id
        self.reason = reason
        msg = f"engine restarted while request {request_id} was in flight"
        if reason:
            msg += f" ({reason})"
        super().__init__(msg + "; retry the request")


class NonFiniteLogits(FloatingPointError):
    """This request's decode produced a non-finite logit window; it errors
    alone — co-batched slots are row-independent and finish unaffected."""


class _StaleEngine(Exception):
    """Internal: the scheduler generation this thread was started for was
    superseded by a restart; abort without touching engine state."""


class EngineRequest:
    """Handle for one submitted generation: streaming callback target,
    completion event, deadline/cancellation, and timing for the serving
    gauges.  Lifecycle: queued → prefilling → decoding → one of
    {eos, length, timeout, cancelled, restarted, error} — exactly once."""

    def __init__(self, rid, prompt, max_new_tokens, temperature, eos_token_id,
                 on_token, deadline_s=None, trace=None, spec_k=None,
                 adapter=None):
        self.id = int(rid)
        # (trace_id, parent_span_id) from the submitting hop, or None;
        # every engine-stage span for this request parents under it
        self.trace = trace
        self.prompt = prompt  # np.int32 [L]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_token_id = eos_token_id
        # per-request speculation cap: None = engine default, 0 = opt out,
        # >0 clamps below the engine-wide FLAGS_serve_spec_k
        self.spec_k = None if spec_k is None else int(spec_k)
        # resolved LoRAAdapter (None = base model); adapter_slot is the
        # arena row this request's binding ref pins, set at admission
        self.adapter = adapter
        self.adapter_slot = None
        self.on_token = on_token
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.tokens = []  # generated ids (includes eos when hit)
        self.finished = threading.Event()
        self.finish_reason = None  # eos|length|timeout|cancelled|restarted|error
        self.state = "queued"  # live phase; finish_reason once terminal
        self.cancelled = False
        self.error = None
        # disaggregated serving (ISSUE 19): export_kv asks the engine to
        # read the committed prompt pages into kv_export at finish; handoff
        # is the (deserialized layers, first_token) pair a decode-role
        # engine imports instead of prefilling; reservation names the
        # decode-side page hold this admission consumes
        self.export_kv = False
        self.kv_export = None
        self.handoff = None
        self.reservation = None
        # session KV (ISSUE 20): session_id names the multi-turn KV hold
        # this request rides; session_reused_tokens counts prompt tokens
        # whose KV came from the session's pinned pages (skipped prefill)
        self.session_id = None
        self.session_reused_tokens = 0
        self.ttft_s = None
        self._submit_t = None
        self._deadline_t = None  # absolute perf_counter deadline
        self._finish_t = None

    def cancel(self):
        """Ask the scheduler to evict this request at its next step: a
        queued request resolves without ever taking a slot, a slotted one
        has its slot recycled (no recompile).  Idempotent; resolution is
        still exactly-once (`finish_reason == "cancelled"`)."""
        self.cancelled = True
        return self

    def expired(self, now=None):
        if self._deadline_t is None:
            return False
        return (time.perf_counter() if now is None else now) >= self._deadline_t

    def wait(self, timeout=None):
        """Block until the request finishes; returns prompt + generated ids.
        Raises a TimeoutError naming the request and its live state when
        `timeout` elapses first (never a None-ish partial result), and
        re-raises the request's typed error (DeadlineExceeded,
        RequestCancelled, EngineRestarted, NonFiniteLogits, ...) when the
        request resolved unsuccessfully."""
        if not self.finished.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not finished after {timeout}s "
                f"(state={self.state}, "
                f"{len(self.tokens)}/{self.max_new_tokens} tokens)"
            )
        if self.error is not None:
            raise self.error
        return np.concatenate([self.prompt, np.asarray(self.tokens, np.int32)])


class ContinuousBatchingEngine:
    """Slot-pooled continuous-batching engine over a causal-LM with the
    compiled static-KV decode contract (`model.llama(toks, caches=, pos=)` +
    `model.lm_head`, i.e. LlamaForCausalLM and shape-compatible models).

    submit() enqueues (bounded admission queue -> QueueFull, deadline-aware
    admission -> DeadlineUnattainable); the scheduler — either the
    background thread started by start()/serve(), or synchronous
    step()/run_until_idle() calls — admits queued requests into free slots
    via bucketed prefill and advances all active slots one token per decode
    step.  Tokens stream through per-request `on_token` callbacks as they
    are produced.  Pair with `fault.EngineSupervisor` for watchdogged
    restart-with-backoff of a wedged/dead scheduler.
    """

    def __init__(self, model, slots=None, max_len=None, prefill_buckets=None,
                 queue_depth=None, seed=0, paged=None, page_size=None,
                 pool_pages=None, prefix_cache=None, spec_k=None, lora=None,
                 decode_kernel=None, tp=None, kv_quant=None, role=None,
                 cp=None, session_max=None):
        import jax

        from .. import jit, to_tensor

        cfg = model.config
        self.model = model
        self.slots = int(slots if slots is not None else _fcore.flag("FLAGS_serve_slots"))
        max_len = max_len if max_len is not None else cfg.max_position_embeddings
        # rope tables (and therefore positions) top out at max_position_embeddings
        self.max_len = int(min(max_len, cfg.max_position_embeddings))
        if prefill_buckets is None:
            raw = str(_fcore.flag("FLAGS_serve_prefill_buckets"))
            prefill_buckets = [int(x) for x in raw.split(",") if x.strip()]
        self.prefill_buckets = sorted(
            {int(b) for b in prefill_buckets if 0 < int(b) < self.max_len}
        )
        if not self.prefill_buckets:
            raise ValueError("prefill_buckets must contain a value < max_len")
        self.queue_depth = int(
            queue_depth if queue_depth is not None else _fcore.flag("FLAGS_serve_queue_depth")
        )

        # generation is inference: dropout must not bake into the cached
        # executables (they outlive any later train() switch)
        if getattr(model, "training", False):
            model.eval()

        # tensor-parallel serving (ISSUE 14): validate + install the 'mp'
        # mesh and re-place the weights BEFORE any cache/arena below is
        # allocated, so every serving buffer is born with its mesh layout.
        # All per-slot scheduling state stays host-side and replicated —
        # the compiled budget and zero-recompile contract are unchanged.
        from .. import profiler as _prof
        from ..distributed import mesh as _mesh_mod
        from ..distributed.sharding import ShardingError, validate_tp

        self.tp = int(_fcore.flag("FLAGS_serve_tp") if tp is None else tp)
        validate_tp(cfg, self.tp)
        # context-parallel serving (ISSUE 20): 'cp' composes with 'mp' —
        # the paged arena's PAGE axis block-shards over cp shards while kv
        # heads shard over mp.  Validated here, typed errors at
        # construction; all host-side page bookkeeping becomes per-shard
        # (PagePool shards, round-robin sequence-page placement).
        self.cp = int(_fcore.flag("FLAGS_serve_cp") if cp is None else cp)
        if self.cp < 1:
            raise ShardingError(f"cp must be >= 1, got {self.cp}")
        self._mesh = None
        if self.tp > 1:
            if int(getattr(cfg, "tensor_parallel_degree", 1)) != self.tp:
                raise ShardingError(
                    f"engine tp={self.tp} but the model was built with "
                    f"tensor_parallel_degree={cfg.tensor_parallel_degree}: "
                    "construct the model with LlamaConfig(tensor_parallel_"
                    f"degree={self.tp}) so its projections are the column/"
                    "row-parallel layers the mesh shards"
                )
        if self.tp > 1 or self.cp > 1:
            self._mesh = _mesh_mod.serving_mesh(self.tp, cp=self.cp)
            if self.tp > 1:
                from ..models.llama import shard_llama_for_tp

                shard_llama_for_tp(model)
        # per compiled step at TP>1, GSPMD inserts one allreduce per
        # row-parallel output (o_proj + down_proj per layer) plus one for
        # the vocab-sharded logits' sampling reduction
        _prof.record_mesh_topology(
            devices=len(jax.devices()),
            tp=self.tp,
            cp=self.cp,
            # ISSUE 20: cp adds one online-softmax partials combine (pmax +
            # 2x psum, fused) per layer per decode step on top of the TP
            # row-parallel allreduces
            allreduce_per_step=(
                (2 * cfg.num_hidden_layers + 1 if self.tp > 1 else 0)
                + (cfg.num_hidden_layers if self.cp > 1 else 0)
            ),
        )

        head_dim = cfg.hidden_size // cfg.num_attention_heads
        cache_dtype = model.lm_head.weight.dtype  # bf16 under AMP-O2 decorate
        self.paged = bool(
            _fcore.flag("FLAGS_serve_paged_kv") if paged is None else paged
        )
        # quantized KV serving (ISSUE 18): validated HERE — typed
        # QuantConfigError at construction, never a dtype mismatch inside a
        # compiled step — and folded into every cache-key surface: the
        # arenas' int8/scale avals, the paged_flash_decode closure, and the
        # FLAGS_serve_kv_quant entries in ops.dispatch._dispatch_salt and
        # the AOT snapshot fingerprint
        self.kv_quant = validate_kv_quant(
            _fcore.flag("FLAGS_serve_kv_quant") if kv_quant is None
            else kv_quant,
            paged=self.paged,
        )
        # disaggregated serving (ISSUE 19): the role decides which side of
        # the paged-KV handoff this engine plays.  'prefill' exports its
        # committed prompt pages at finish; 'decode' grows ONE extra
        # compiled executable (the page-import scatter) and accepts
        # handoff submissions; 'colocated' is the classic single-box
        # engine with an unchanged compiled budget.
        self.role = str(
            _fcore.flag("FLAGS_serve_role") if role is None else role
        ).strip().lower()
        if self.role not in ("colocated", "prefill", "decode"):
            raise ValueError(
                f"role must be colocated|prefill|decode, got {self.role!r}"
            )
        if self.role != "colocated" and not self.paged:
            raise ValueError(
                f"role={self.role!r} requires the paged engine: the "
                "prefill->decode handoff rides the page arenas"
            )
        if self.cp > 1 and not self.paged:
            raise ShardingError(
                f"cp={self.cp} requires the paged engine: context "
                "parallelism shards the page arena, not dense slot buffers"
            )
        if self.cp > 1 and self.role != "colocated":
            raise ShardingError(
                f"cp={self.cp} with role={self.role!r}: the disaggregated "
                "handoff assumes single-shard page ownership; run cp on "
                "colocated replicas"
            )
        if self.paged:
            ps = int(
                page_size if page_size is not None
                else _fcore.flag("FLAGS_serve_kv_page_size")
            )
            # a page never needs to exceed a sequence; clamping keeps the
            # default flag sane for tiny test engines
            self.page_size = max(1, min(ps, self.max_len))
            self.pages_per_seq = -(-self.max_len // self.page_size)
            if self.cp > 1:
                # per-shard geometry: sequence page k lives on shard k % cp,
                # so the table width pads to a cp multiple (shard s's local
                # table is exactly columns {s, s+cp, ...}) and every shard
                # holds pages_per_seq/cp entries of a full-length sequence
                self.pages_per_seq = -(-self.pages_per_seq // self.cp) * self.cp
            # paged-attention kernel selection (ISSUE 13): validated HERE so
            # a forced-fused engine fails at construction, not mid-traffic
            # inside a compiled step
            dk = str(
                _fcore.flag("FLAGS_serve_decode_kernel")
                if decode_kernel is None else decode_kernel
            )
            if dk not in ("auto", "fused", "gather"):
                raise ValueError(
                    f"decode_kernel must be auto|fused|gather, got {dk!r}"
                )
            if dk == "fused":
                head_ok = head_dim <= 256
                page_ok = self.page_size % 8 == 0
                if not (head_ok and page_ok):
                    raise ValueError(
                        "decode_kernel='fused' needs head_dim <= 256 and a "
                        f"sublane-aligned page_size (8|ps); got head_dim="
                        f"{head_dim}, page_size={self.page_size}"
                    )
            self.decode_kernel = dk
            pp = int(
                pool_pages if pool_pages is not None
                else _fcore.flag("FLAGS_serve_kv_pool_pages")
            )
            cache_dtype_bytes = int(
                np.dtype(_fcore.to_jax_dtype(cache_dtype)).itemsize
            )
            if pp <= 0:  # auto: every slot can hold a max_len sequence
                pp = self.slots * self.pages_per_seq + 1
                if self.cp > 1:
                    # PER-SHARD auto-sizing (ISSUE 20): each shard stores
                    # pages_per_seq/cp pages of every slot's sequence plus
                    # its own scratch page — the pool total is cp * that,
                    # the same per-device HBM budget as the cp=1 pool
                    pp = self.cp * (
                        self.slots * (self.pages_per_seq // self.cp) + 1
                    )
                if self.kv_quant == "int8":
                    # same HBM budget, more pages: the auto pool holds the
                    # BYTES of the full-precision pool, so the int8 arena's
                    # page count scales by full_page_bytes / (int8 page +
                    # its scale rows) — ~1.94x at bf16 head_dim=128.  Scale
                    # bytes are charged here, not hidden: the ratio uses
                    # kv_page_bytes which counts the 4-byte f32 scale per
                    # (row, kv head)
                    full = kv_page_bytes(
                        self.page_size, cfg.num_key_value_heads, head_dim,
                        cache_dtype_bytes, "none",
                    )
                    q8 = kv_page_bytes(
                        self.page_size, cfg.num_key_value_heads, head_dim,
                        cache_dtype_bytes, "int8",
                    )
                    pp = (self.slots * self.pages_per_seq * full) // q8 + 1
            if self.cp > 1:
                # the pool block-shards over cp: equal per-shard ranges,
                # each with its own scratch page at the range head
                pp = max(pp, 2 * self.cp)
                pp = -(-pp // self.cp) * self.cp
            self.pool_pages = int(pp)
            self._caches = None
            self._arenas = [
                PagedKVCache(self.pool_pages, self.page_size,
                             cfg.num_key_value_heads, head_dim, cache_dtype,
                             quant=self.kv_quant)
                for _ in range(cfg.num_hidden_layers)
            ]
            if self.tp > 1 or self.cp > 1:
                for a in self._arenas:
                    shard_kv_for_tp(a)
            # observability (ISSUE 18): arena + scale HBM bytes as set (not
            # accumulated) gauges, all layers included — /metrics renders
            # them as paddle_kv_quant_*
            page_b = kv_page_bytes(
                self.page_size, cfg.num_key_value_heads, head_dim,
                cache_dtype_bytes, self.kv_quant,
            )
            scale_b = (
                2 * self.page_size * cfg.num_key_value_heads * 4
                if self.kv_quant == "int8" else 0
            )
            _prof.record_kv_quant(
                mode=self.kv_quant,
                arena_bytes=cfg.num_hidden_layers * self.pool_pages
                * (page_b - scale_b),
                scale_bytes=cfg.num_hidden_layers * self.pool_pages * scale_b,
            )
            self._pool = PagePool(self.pool_pages, shards=self.cp)
            use_prefix = bool(
                _fcore.flag("FLAGS_serve_prefix_cache")
                if prefix_cache is None else prefix_cache
            )
            self._prefix = PrefixCache(self.page_size) if use_prefix else None
            # session KV (ISSUE 20): named multi-turn holds on prefix-cache
            # chains.  Rides the prefix cache — without it, session_id still
            # parses but every turn re-prefills statelessly.
            self._sessions = (
                SessionStore(capacity=int(
                    _fcore.flag("FLAGS_serve_session_max")
                    if session_max is None else session_max
                ))
                if self._prefix is not None else None
            )
            # ignore sub-threshold matches: an accidental few-token overlap
            # between unrelated prompts must not flip a request onto the
            # chunk-prefill path (and its different first-token rounding)
            self.min_prefix_match = 8
            self._page_table = np.zeros(
                (self.slots, self.pages_per_seq), np.int32
            )
            self._slot_pages = [[] for _ in range(self.slots)]
            self._tables_t = None  # device mirror, rebuilt with _dev
            self._decode_fn = jit.to_static(self._decode_paged_body)
            self._prefill_fn = jit.to_static(self._prefill_paged_body)
            self._chunk_fn = jit.to_static(self._chunk_prefill_body)
            self._copy_fn = jit.to_static(self._copy_page_body)
            # handoff geometry, captured once: submit() validates incoming
            # payloads against it and the exporter stamps it on the wire
            self._kv_heads = int(cfg.num_key_value_heads)
            self._head_dim = int(head_dim)
            self._kv_dtype_np = np.dtype(_fcore.to_jax_dtype(cache_dtype))
            # the import scatter is built ONLY for decode-role engines, so
            # colocated/prefill compile_counts() keep their exact dict shape
            self._import_fn = (
                jit.to_static(
                    self._import_page_q8_body if self.kv_quant == "int8"
                    else self._import_page_body
                )
                if self.role == "decode" else None
            )
        else:
            self._arenas = None
            self._pool = None
            self._prefix = None
            self._sessions = None
            self._import_fn = None
            self.decode_kernel = "auto"  # dense engines have no paged path
            self._caches = [
                StaticKVCache(self.slots, self.max_len, cfg.num_key_value_heads,
                              head_dim, cache_dtype)
                for _ in range(cfg.num_hidden_layers)
            ]
            if self.tp > 1:
                for c in self._caches:
                    shard_kv_for_tp(c)
            self._decode_fn = jit.to_static(self._decode_body)
            self._prefill_fn = jit.to_static(self._prefill_body)
        # multi-tenant LoRA (ISSUE 12): an AdapterArena whose per-slot ids
        # ride the paged executables as DATA — co-batched slots on different
        # adapters share one compiled step, id 0 is the base passthrough
        if lora is not None and not self.paged:
            raise ValueError("LoRA serving requires the paged engine")
        self._lora = lora
        if lora is not None and self.tp > 1:
            lora.shard_for_tp()
        # arena slot bound per ENGINE slot (0 = base model); mirrors
        # _page_table's lifecycle: set at slot landing, cleared at recycle
        self._slot_adapter = np.zeros(self.slots, np.int32)
        self._adapters_t = None  # device mirror, rebuilt with _dev
        # speculative decoding (paged engines only — it rides the page
        # scatter's scratch redirect for rejected-row safety)
        sk = int(_fcore.flag("FLAGS_serve_spec_k") if spec_k is None else spec_k)
        if sk < 0:
            raise ValueError("spec_k must be >= 0")
        self.spec_k = sk if self.paged else 0
        self._spec_on = self.spec_k > 0
        self._spec_ngram = int(_fcore.flag("FLAGS_serve_spec_ngram"))
        self._verify_fn = (
            jit.to_static(self._verify_paged_body) if self._spec_on else None
        )
        self._drafters = [None] * self.slots  # per-slot NgramDrafter or None
        # EWMA of emitted tokens per slot-step (1.0 without speculation) —
        # the drain estimate divides by it so admission stays honest when
        # verify steps emit accepted runs
        self._tok_rate_ewma = 1.0
        self._key = to_tensor(np.asarray(jax.random.PRNGKey(int(seed))))

        # runtime-sanitizer bookkeeping: after warmup() the scheduler tick
        # runs inside a steady_state region (every fresh trace/compile/sync
        # in it is a finding); buckets traced so far are tracked so the
        # legitimate over-bucket growth path can declare itself allowed
        self._warmed = False
        self._warm_buckets = set()

        # host-side slot table — mutated only under _mu, by the scheduler
        # generation that owns the engine (restart supersedes via _gen)
        self._slot_req = [None] * self.slots
        self._pos = np.zeros(self.slots, np.int32)
        self._last_tok = np.zeros(self.slots, np.int32)
        self._temps = np.zeros(self.slots, np.float32)
        # device-resident decode loop state (toks, pos, active, temps),
        # rebuilt from the host mirrors only when slot membership changes
        self._dev = None
        # open decode-epoch summary for tracing: {"t0", "ticks", "members"},
        # one engine.decode span per traced member when membership changes
        self._ep = None
        # decode steps dispatched but not yet fetched to host:
        # [(nxt, finite, active_idx, dispatch_t)]
        self._pending_fetch = []
        # all-False poison vector reused every un-poisoned step (no per-step
        # H2D); serve.decode.nan swaps in a one-hot row for one step
        self._poison_zero = to_tensor(np.zeros(self.slots, bool))

        self._queue = queue.Queue(maxsize=self.queue_depth)
        self._requeue = []  # restart-recovered requests, ahead of the queue
        self._queued_new_tokens = 0  # tokens owed to queued+requeued work
        self._admitting = None  # request between queue-pop and slot landing
        # disaggregated page reservations (ISSUE 19): rid -> (pages, expiry).
        # A reservation is a PLAIN COUNTER against fresh-allocation headroom,
        # never a fake pool refcount — the page-invariant audit demands refs
        # equal observable holds exactly.  Expired entries are purged every
        # scheduler tick (TTL covers a router that died mid-handoff).
        self._reserved = {}
        self._reserved_pages = 0
        self._cv = threading.Condition()
        self._mu = threading.RLock()  # slot table / device state / requeue
        self._thread = None
        self._stop = False

        # fault domain: generation counter fences restarted-away schedulers;
        # the per-engine watchdog records trips instead of exiting
        self._gen = 0
        self._dead = False
        self._draining = False
        self.restart_count = 0
        self._watchdog = _wd.Watchdog(action=self._on_watchdog)
        self._watchdog_trip = None  # (region, deadline_s) set by the monitor
        self._last_progress = time.monotonic()
        self._step_ewma_s = None  # EWMA wall seconds per decode round
        # deadline-miss RATE over terminal resolutions (EWMA, not the
        # monotonic faults counter): 1.0 for a timeout eviction, 0.0 for a
        # normal finish, blended at _MISS_EWMA_ALPHA — the autoscaler and
        # brownout logic need "how often are we missing NOW", which a
        # running total cannot answer without a scrape-side derivative
        self._miss_ewma = 0.0

    # -- compiled bodies ----------------------------------------------------

    def _decode_body(self, toks, pos, active, temps, poison, key):
        """One token for every slot: toks [S,1], pos [S], active [S] bool,
        temps [S] f32 (0 = greedy, >0 = sampled — per-slot, as data), poison
        [S] bool (chaos-only NaN injection — identity when all-False), key
        uint32[2].  Inactive slots run at pos 0 (scratch, see module doc).
        Returns (next tokens [S,1], advanced pos [S], finite [S], key): the
        loop state is device-resident and threads straight back in — between
        membership changes a decode step costs one executable dispatch plus
        the [S] token fetch, zero host->device transfers.  `finite` is the
        per-slot non-finite-logit-window watch: a poisoned/diverged slot
        errors alone, its co-batched rows are independent."""
        import jax
        import jax.numpy as jnp

        from ..ops.dispatch import apply

        pos_eff = apply(
            lambda p, a: jnp.where(a, p, 0), [pos, active], name="serve_pos_mask"
        )
        hidden, _ = self.model.llama(toks, caches=self._caches, pos=pos_eff)
        logits = self.model.lm_head(hidden)[:, -1]  # [S, V]

        def f(lg, ky, tp, p, a, po):
            lgf = lg.astype(jnp.float32)
            lgf = jnp.where(po[:, None], jnp.nan, lgf)
            finite = jnp.all(jnp.isfinite(lgf), axis=-1) | ~a
            greedy = jnp.argmax(lgf, axis=-1).astype(jnp.int32)
            ky, sub = jax.random.split(ky)
            samp = jax.random.categorical(
                sub, lgf / jnp.maximum(tp, 1e-6)[:, None], axis=-1
            ).astype(jnp.int32)
            nxt = jnp.where(tp > 0.0, samp, greedy)
            return nxt[:, None], jnp.where(a, p + 1, p), finite, ky

        nxt, new_pos, finite, key = apply(
            f, [logits, key, temps, pos, active, poison], multi=True,
            name="serve_sample",
        )
        return nxt, new_pos, finite, key

    def _prefill_body(self, toks, slot, true_len, temp, key):
        """Bucketed prefill: toks [1, bucket] (right-padded), slot / true_len
        scalars (data).  Writes K/V into pool rows [0, bucket) of `slot` and
        returns the first generated token from the logits at true_len - 1."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops.dispatch import apply

        views = [SlotView(c, slot) for c in self._caches]
        hidden, _ = self.model.llama(toks, caches=views)
        h_last = apply(
            lambda h, n: lax.dynamic_slice_in_dim(h, n - 1, 1, 1),
            [hidden, true_len], name="serve_prefill_last",
        )
        logits = self.model.lm_head(h_last)[:, -1]  # [1, V]

        def f(lg, ky, tp):
            lgf = lg.astype(jnp.float32)
            greedy = jnp.argmax(lgf, axis=-1).astype(jnp.int32)
            ky, sub = jax.random.split(ky)
            samp = jax.random.categorical(
                sub, lgf / jnp.maximum(tp, 1e-6), axis=-1
            ).astype(jnp.int32)
            return jnp.where(tp > 0.0, samp, greedy), ky

        nxt, key = apply(f, [logits, key, temp], multi=True, name="serve_sample1")
        return nxt, key

    def _decode_paged_body(self, toks, pos, active, temps, poison, key, tables,
                           adapters):
        """_decode_body over the paged arena: identical math, but each slot's
        K/V rows are gathered through its page-table row (`tables`
        [slots, max_pages_per_seq] int32 — DATA, so remaps never retrace).
        `adapters` [slots] int32 (data too) names each slot's LoRA arena row;
        with an arena attached every projection adds the gathered low-rank
        delta, and row 0 (all-zero factors) keeps base-model slots
        bit-identical.  Bit-identical tokens to the dense decode given
        identical cache rows: the gather reproduces the dense
        [slots, max_len] geometry exactly and rows beyond `pos` are masked
        to zero weight either way."""
        import jax
        import jax.numpy as jnp

        from ..ops.dispatch import apply

        pos_eff = apply(
            lambda p, a: jnp.where(a, p, 0), [pos, active], name="serve_pos_mask"
        )
        views = [
            PagedDecodeView(a, tables, self.max_len, kernel=self.decode_kernel)
            for a in self._arenas
        ]
        lora = self._lora.view(adapters) if self._lora is not None else None
        hidden, _ = self.model.llama(toks, caches=views, pos=pos_eff, lora=lora)
        logits = self.model.lm_head(hidden)[:, -1]  # [S, V]

        def f(lg, ky, tp, p, a, po):
            lgf = lg.astype(jnp.float32)
            lgf = jnp.where(po[:, None], jnp.nan, lgf)
            finite = jnp.all(jnp.isfinite(lgf), axis=-1) | ~a
            greedy = jnp.argmax(lgf, axis=-1).astype(jnp.int32)
            ky, sub = jax.random.split(ky)
            samp = jax.random.categorical(
                sub, lgf / jnp.maximum(tp, 1e-6)[:, None], axis=-1
            ).astype(jnp.int32)
            nxt = jnp.where(tp > 0.0, samp, greedy)
            return nxt[:, None], jnp.where(a, p + 1, p), finite, ky

        nxt, new_pos, finite, key = apply(
            f, [logits, key, temps, pos, active, poison], multi=True,
            name="serve_sample",
        )
        return nxt, new_pos, finite, key

    def _verify_paged_body(self, toks, pos, active, valid_len, temps, poison,
                           key, tables, adapters):
        """Speculative verify: ONE compiled forward scores k+1 positions per
        slot.  toks [S, k+1] — column 0 the committed last token (not yet in
        KV; this window writes it), columns 1..k the host-side prompt-lookup
        drafts; valid_len [S] counts the committed token plus real drafts
        (1 == plain decode for that row).  Window row i writes KV at pos+i
        through the page table and attends j <= pos+i, so greedy[i] is the
        model's next token after prefix + window[:i+1].  Draft i is accepted
        iff it equals greedy[i-1] and every earlier draft was (cumulative
        product), and the emitted run is greedy[0..n_acc] — exactly the
        tokens one-at-a-time decode would have produced (greedy
        equivalence; draft quality only moves the acceptance rate).
        Rejected rows need no rollback: their KV sits past the advanced pos
        (or on scratch via the table redirect) and the next window rewrites
        [new_pos, new_pos+k] before anything attends it.  Sampled slots
        (temp > 0) ride at valid_len 1; column 0 samples on the SAME
        one-split-per-step key schedule as `_decode_paged_body`.  The verify
        window gathers the same per-slot `adapters` ids as plain decode, so
        speculation composes with multi-tenant LoRA: greedy equivalence is
        per-adapter (draft i accepted only while it matches THAT adapter's
        greedy continuation).  Returns (out [S,k+1], n_emit [S],
        new_pos [S], finite [S], key)."""
        import jax
        import jax.numpy as jnp

        from ..ops.dispatch import apply

        pos_eff = apply(
            lambda p, a: jnp.where(a, p, 0), [pos, active], name="serve_pos_mask"
        )
        views = [
            PagedDecodeView(a, tables, self.max_len, kernel=self.decode_kernel)
            for a in self._arenas
        ]
        lora = self._lora.view(adapters) if self._lora is not None else None
        hidden, _ = self.model.llama(toks, caches=views, pos=pos_eff, lora=lora)
        logits = self.model.lm_head(hidden)  # [S, k+1, V]

        def f(lg, tk, ky, tp, p, a, vl, po):
            lgf = lg.astype(jnp.float32)
            lgf = jnp.where(po[:, None, None], jnp.nan, lgf)
            greedy = jnp.argmax(lgf, axis=-1).astype(jnp.int32)  # [S, k+1]
            k1 = greedy.shape[1]
            drafts_ok = tk[:, 1:] == greedy[:, :-1]
            considered = (
                jnp.arange(k1 - 1, dtype=jnp.int32)[None, :] < (vl - 1)[:, None]
            )
            acc = jnp.cumprod((drafts_ok & considered).astype(jnp.int32), axis=1)
            n_acc = acc.sum(axis=1).astype(jnp.int32)
            n_emit = jnp.where(a, n_acc + 1, 0).astype(jnp.int32)
            ky, sub = jax.random.split(ky)
            samp0 = jax.random.categorical(
                sub, lgf[:, 0] / jnp.maximum(tp, 1e-6)[:, None], axis=-1
            ).astype(jnp.int32)
            out = greedy.at[:, 0].set(jnp.where(tp > 0.0, samp0, greedy[:, 0]))
            # the non-finite watch covers only EMITTED rows: a rejected
            # draft's logits are discarded, they must not error the slot
            row_finite = jnp.all(jnp.isfinite(lgf), axis=-1)  # [S, k+1]
            emit_mask = (
                jnp.arange(k1, dtype=jnp.int32)[None, :] < n_emit[:, None]
            )
            finite = jnp.all(row_finite | ~emit_mask, axis=1) | ~a
            return out, n_emit, p + n_emit, finite, ky

        out, n_emit, new_pos, finite, key = apply(
            f, [logits, toks, key, temps, pos, active, valid_len, poison],
            multi=True, name="serve_verify",
        )
        return out, n_emit, new_pos, finite, key

    def _prefill_paged_body(self, toks, row_table, true_len, temp, key,
                            adapters):
        """_prefill_body for a fresh paged prefill: the prompt attends to
        itself causally (the exact dense-SlotView math — bit-identical first
        tokens) while its K/V scatter into the pages of `row_table`
        ([max_pages_per_seq] int32, data).  `adapters` ([1] int32, data) is
        the request's LoRA arena row (0 = base).  Padding rows land on
        scratch."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops.dispatch import apply

        views = [
            PagedPrefillView(a, row_table, true_len, self.max_len,
                             kernel=self.decode_kernel)
            for a in self._arenas
        ]
        lora = self._lora.view(adapters) if self._lora is not None else None
        hidden, _ = self.model.llama(toks, caches=views, lora=lora)
        h_last = apply(
            lambda h, n: lax.dynamic_slice_in_dim(h, n - 1, 1, 1),
            [hidden, true_len], name="serve_prefill_last",
        )
        logits = self.model.lm_head(h_last)[:, -1]  # [1, V]

        def f(lg, ky, tp):
            lgf = lg.astype(jnp.float32)
            greedy = jnp.argmax(lgf, axis=-1).astype(jnp.int32)
            ky, sub = jax.random.split(ky)
            samp = jax.random.categorical(
                sub, lgf / jnp.maximum(tp, 1e-6), axis=-1
            ).astype(jnp.int32)
            return jnp.where(tp > 0.0, samp, greedy), ky

        nxt, key = apply(f, [logits, key, temp], multi=True, name="serve_sample1")
        return nxt, key

    def _chunk_prefill_body(self, toks, row_table, true_len, start, temp, key,
                            adapters):
        """Prefix-cache-hit prefill: only the UNSHARED suffix runs through
        the model.  toks [1, bucket] holds the suffix (right-padded),
        true_len its real length, start (int32[1], data) the absolute
        position of suffix row 0 — suffix row i writes page
        table[(start+i)//ps] and attends positions j <= start+i through the
        table gather, shared prefix pages included.  `adapters` ([1] int32,
        data) is the request's LoRA arena row — safe to combine with prefix
        sharing because cache entries are keyed by (adapter, token chain):
        a hit guarantees the shared pages were prefilled under the SAME
        adapter."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops.dispatch import apply

        views = [
            PagedPrefillView(a, row_table, true_len, self.max_len, start=start,
                             kernel=self.decode_kernel)
            for a in self._arenas
        ]
        lora = self._lora.view(adapters) if self._lora is not None else None
        hidden, _ = self.model.llama(toks, caches=views, lora=lora)
        h_last = apply(
            lambda h, n: lax.dynamic_slice_in_dim(h, n - 1, 1, 1),
            [hidden, true_len], name="serve_prefill_last",
        )
        logits = self.model.lm_head(h_last)[:, -1]  # [1, V]

        def f(lg, ky, tp):
            lgf = lg.astype(jnp.float32)
            greedy = jnp.argmax(lgf, axis=-1).astype(jnp.int32)
            ky, sub = jax.random.split(ky)
            samp = jax.random.categorical(
                sub, lgf / jnp.maximum(tp, 1e-6), axis=-1
            ).astype(jnp.int32)
            return jnp.where(tp > 0.0, samp, greedy), ky

        nxt, key = apply(f, [logits, key, temp], multi=True, name="serve_sample1")
        return nxt, key

    def _copy_page_body(self, src, dst):
        """Copy-on-write: duplicate arena page `src` into `dst` (scalar int32
        Tensors — data) across every layer's K and V, inside ONE compiled
        dispatch.  Used exactly once per admission that extends a partially
        filled shared page; decode never copies (frontier pages are always
        exclusively owned).  Under an int8 arena the COW tail carries its
        SCALE rows too — the copy dequantizes identically to its source,
        and the writer's appends requantize only its own new rows."""
        from ..ops.dispatch import apply

        def f(c, s_, d_):
            return c.at[d_].set(c[s_])

        for a in self._arenas:
            a.k._data = apply(f, [a.k, src, dst], name="kv_page_copy")._data
            a.v._data = apply(f, [a.v, src, dst], name="kv_page_copy")._data
            if a.k_scale is not None:
                a.k_scale._data = apply(
                    f, [a.k_scale, src, dst], name="kv_page_copy"
                )._data
                a.v_scale._data = apply(
                    f, [a.v_scale, src, dst], name="kv_page_copy"
                )._data
        return dst

    def _import_page_body(self, k_tiles, v_tiles, dst):
        """Disaggregated handoff import (ISSUE 19): land ONE page's worth of
        prompt K/V rows — shipped by a prefill worker — into arena page
        `dst` across every layer, in one compiled dispatch.  `k_tiles` /
        `v_tiles` are `[n_layers, page_size, kv_heads, head_dim]` stacks
        (partial last pages arrive zero-padded; padded rows sit past the
        slot's pos, masked like any other garbage row) and `dst` a scalar
        int32 — ALL data, so the decode worker imports any number of
        handoffs through this single executable with zero recompiles."""
        from ..ops.dispatch import apply

        for i, a in enumerate(self._arenas):
            a.k._data = apply(
                lambda c, t, d_, _i=i: c.at[d_].set(t[_i]),
                [a.k, k_tiles, dst], name="kv_page_import",
            )._data
            a.v._data = apply(
                lambda c, t, d_, _i=i: c.at[d_].set(t[_i]),
                [a.v, v_tiles, dst], name="kv_page_import",
            )._data
        return dst

    def _import_page_q8_body(self, k_tiles, v_tiles, k_scale_tiles,
                             v_scale_tiles, dst):
        """`_import_page_body` for an int8 arena: the handoff ships the
        quantized rows AS STORED plus their float32 scale rows
        (`[n_layers, page_size, kv_heads, 1]` stacks), so the import writes
        bit-identical arena state — no requantization, no drift, and the
        wire pays int8 prices (~2x cheaper than the cache dtype)."""
        from ..ops.dispatch import apply

        for i, a in enumerate(self._arenas):
            a.k._data = apply(
                lambda c, t, d_, _i=i: c.at[d_].set(t[_i]),
                [a.k, k_tiles, dst], name="kv_page_import",
            )._data
            a.v._data = apply(
                lambda c, t, d_, _i=i: c.at[d_].set(t[_i]),
                [a.v, v_tiles, dst], name="kv_page_import",
            )._data
            a.k_scale._data = apply(
                lambda c, t, d_, _i=i: c.at[d_].set(t[_i]),
                [a.k_scale, k_scale_tiles, dst], name="kv_page_import",
            )._data
            a.v_scale._data = apply(
                lambda c, t, d_, _i=i: c.at[d_].set(t[_i]),
                [a.v_scale, v_scale_tiles, dst], name="kv_page_import",
            )._data
        return dst

    # -- public API ---------------------------------------------------------

    def submit(self, input_ids, max_new_tokens=32, temperature=0.0,
               eos_token_id=None, on_token=None, deadline_s=None,
               trace=None, spec_k=None, adapter=None, export_kv=False,
               handoff=None, reservation=None, session_id=None):
        """Enqueue one request (1-D token ids).  Returns an EngineRequest
        handle immediately; raises QueueFull when the admission queue is at
        capacity, DeadlineUnattainable when `deadline_s` cannot beat the
        current queue-drain estimate (deadline-aware admission), and
        EngineUnavailable while draining or after the restart budget is
        spent.  `spec_k` caps this request's speculative draft length below
        the engine-wide FLAGS_serve_spec_k (0 opts out, None = default).
        `adapter` names a registered LoRA adapter (name or stable id; None
        or 0 = base model) — AdapterUnknown propagates for unregistered
        names, so clients see the typed 404 before the request ever
        queues.  Disaggregated serving (ISSUE 19): `export_kv=True` makes
        a paged engine read the request's committed prompt pages into a
        handoff payload (`req.kv_export`) when it finishes; `handoff`
        carries such a payload INTO a decode-role engine — the prompt's KV
        is imported through the compiled page scatter instead of
        prefilled, and the payload's first token becomes the request's
        first emitted token.  `reservation` names a reserve_pages() hold
        this admission consumes.  `session_id` (ISSUE 20) names a KV
        session: the request chunk-prefills only the suffix past the
        session's pinned pages, and at finish the full committed sequence
        (prompt + generated) is re-bound so turn N+1 resumes from it."""
        from .. import profiler as _prof
        from .paging import HandoffFormatError, deserialize_kv_handoff

        ids = np.asarray(input_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        if ids.size >= self.max_len:
            # typed 400 BEFORE any page is reserved (ISSUE 20): carries the
            # capacity geometry (per-shard under cp) so the client's error
            # body says exactly how much context this tier holds
            raise ContextOverflow(
                ids.size, self.max_len, cp=self.cp,
                pages_per_shard=(
                    (self.pages_per_seq // self.cp) if self.paged else 0
                ),
                page_size=self.page_size if self.paged else 0,
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        if spec_k is not None and int(spec_k) < 0:
            raise ValueError("spec_k must be >= 0")
        adapter_obj = None
        if adapter is not None and adapter != 0:
            if self._lora is None:
                raise ValueError(
                    "engine has no LoRA arena (construct with lora=) but "
                    f"request named adapter {adapter!r}"
                )
            # resolve NOW: an unknown name is terminal (AdapterUnknown ->
            # HTTP 404), a known one is validated against the arena rank cap
            adapter_obj = self._lora.registry.resolve(adapter)
            if adapter_obj.rank > self._lora.rank_max:
                raise ValueError(
                    f"adapter {adapter_obj.name!r} rank {adapter_obj.rank} "
                    f"exceeds the arena rank_max {self._lora.rank_max}"
                )
        if export_kv and not self.paged:
            raise ValueError(
                "export_kv requires the paged engine (the handoff payload "
                "is the committed page rows)"
            )
        handoff_state = None
        if handoff is not None:
            # typed validation BEFORE the request queues: wrong role,
            # foreign arena geometry, or corrupt rows must surface as a
            # client error, never inside a compiled step
            if not (self.paged and self.role == "decode"):
                raise ValueError(
                    "handoff import requires a paged engine in the 'decode' "
                    f"role (this engine: paged={self.paged}, "
                    f"role={self.role!r})"
                )
            if adapter_obj is not None:
                raise ValueError(
                    "handoff requests cannot name a LoRA adapter: the "
                    "prefill worker's exported KV embeds no adapter deltas"
                )
            layers, hL = deserialize_kv_handoff(
                handoff, self.kv_quant, self._kv_heads, self._head_dim,
                len(self._arenas), self._kv_dtype_np.name,
            )
            if hL != int(ids.size):
                raise HandoffFormatError(
                    f"handoff prompt_len {hL} != submitted prompt length "
                    f"{int(ids.size)}"
                )
            first_tok = handoff.get("first_token")
            if first_tok is None:
                raise HandoffFormatError(
                    "handoff payload missing first_token (the prefill "
                    "worker's sampled token)"
                )
            handoff_state = (layers, int(first_tok))
        if self._dead:
            raise EngineUnavailable(
                "engine is dead (restart budget exhausted); restart the server"
            )
        if self._draining:
            raise EngineUnavailable(
                "engine is draining (shutdown in progress)",
                retry_after_s=self.estimate_drain_s(),
            )
        if deadline_s is not None:
            est = self.estimate_drain_s()
            if est > float(deadline_s):
                _prof.record_serving_fault("rejected_deadline")
                _flight.record(
                    "admission", "rejected_deadline",
                    deadline_s=float(deadline_s), drain_est_s=round(est, 3),
                )
                raise DeadlineUnattainable(
                    f"deadline {deadline_s}s cannot beat the current "
                    f"queue-drain estimate {est:.2f}s",
                    retry_after_s=est,
                )
        if self.paged:
            # page-aware admission: a request whose WORST-CASE page need
            # (no prefix sharing assumed) exceeds the pool can never be
            # scheduled — fail fast with the same 503 family the queue
            # bound uses instead of parking it forever
            need = self._pages_for(ids.size, max_new_tokens)
            # under cp the binding bound is PER SHARD: sequence page k only
            # ever comes from shard k % cp, so the worst shard must hold
            # ceil(need / cp) pages out of its per_shard - 1 usable
            if -(-need // self.cp) > self._pool.per_shard - 1:
                raise QueueFull(
                    f"request needs {need} KV pages (prompt {ids.size} + "
                    f"max_new {max_new_tokens} at page size {self.page_size})"
                    f" but the pool holds {self._pool.usable_pages}"
                    + (f" across cp={self.cp} shards" if self.cp > 1 else ""),
                    retry_after_s=self._shed_retry_after(deadline_s),
                )
        req = EngineRequest(
            next(self._req_ids), ids, max_new_tokens, temperature,
            eos_token_id, on_token, deadline_s=deadline_s, trace=trace,
            spec_k=spec_k, adapter=adapter_obj,
        )
        req.export_kv = bool(export_kv)
        req.handoff = handoff_state
        req.reservation = None if reservation is None else str(reservation)
        if session_id is not None:
            if self._sessions is None:
                raise ValueError(
                    "session_id requires a paged engine with a prefix cache "
                    "(construct with paged=True, prefix_cache=True)"
                )
            if handoff_state is not None:
                raise ValueError(
                    "session_id cannot combine with a KV handoff import: "
                    "sessions live on the prefill-owning replica's pages"
                )
            req.session_id = str(session_id)
        req._submit_t = time.perf_counter()
        if deadline_s is not None:
            req._deadline_t = req._submit_t + float(deadline_s)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            _flight.record("admission", "queue_full",
                           queue_depth=self.queue_depth)
            raise QueueFull(
                f"admission queue full ({self.queue_depth} pending)",
                retry_after_s=self._shed_retry_after(deadline_s),
            ) from None
        with self._mu:
            self._queued_new_tokens += req.max_new_tokens
        with self._cv:
            self._cv.notify()
        return req

    _req_ids = itertools.count(1)  # request ids unique across engines

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 eos_token_id=None, timeout=None, adapter=None):
        """Submit + wait.  Drives the scheduler inline when no background
        thread is running; returns prompt + generated ids (np.int32)."""
        req = self.submit(input_ids, max_new_tokens=max_new_tokens,
                          temperature=temperature, eos_token_id=eos_token_id,
                          adapter=adapter)
        if self._thread is None:
            self.run_until_idle()
        return req.wait(timeout)

    def warmup(self):
        """Trace/compile (or AOT-load via FLAGS_compile_cache_dir) every
        prefill bucket and the decode step before traffic arrives.  Dummy
        data through the real executables; the rows it scribbles into slot 0
        are rewritten by that slot's next real prefill.  Call before start().
        """
        from .. import to_tensor

        if self.paged:
            # all-zero tables aim every warmup write at scratch page 0;
            # all-zero adapter ids ride the base (zero-delta) arena row
            zero_row = to_tensor(np.zeros(self.pages_per_seq, np.int32))
            zero_ad1 = to_tensor(np.zeros(1, np.int32))
            zero_ads = to_tensor(np.zeros(self.slots, np.int32))
            for b in self.prefill_buckets:
                # analysis: allow GRAFT010 — warmup runs before the scheduler thread exists; steady-state _key writes hold _mu
                _, self._key = self._prefill_fn(
                    to_tensor(np.zeros((1, b), np.int32)), zero_row,
                    to_tensor(np.int32(b)), to_tensor(np.float32(0.0)),
                    self._key, zero_ad1,
                )
                _, self._key = self._chunk_fn(
                    to_tensor(np.zeros((1, b), np.int32)), zero_row,
                    to_tensor(np.int32(b)),
                    to_tensor(np.zeros(1, np.int32)),
                    to_tensor(np.float32(0.0)), self._key, zero_ad1,
                )
            self._copy_fn(  # scratch onto itself: a no-op through the real fn
                to_tensor(np.int32(0)), to_tensor(np.int32(0))
            )
            if self._import_fn is not None:
                # decode role: warm the handoff import scatter with zero
                # tiles aimed at scratch page 0 (already zeros — a no-op
                # through the real executable, like the copy warm above)
                nl = len(self._arenas)
                elem = (
                    np.dtype(np.int8) if self.kv_quant == "int8"
                    else self._kv_dtype_np
                )
                tile = (nl, self.page_size, self._kv_heads, self._head_dim)
                args = [
                    to_tensor(np.zeros(tile, elem)),
                    to_tensor(np.zeros(tile, elem)),
                ]
                if self.kv_quant == "int8":
                    srow = (nl, self.page_size, self._kv_heads, 1)
                    args += [
                        to_tensor(np.ones(srow, np.float32)),
                        to_tensor(np.ones(srow, np.float32)),
                    ]
                self._import_fn(*args, to_tensor(np.int32(0)))
            _, _, _, self._key = self._decode_fn(
                to_tensor(np.zeros((self.slots, 1), np.int32)),
                to_tensor(np.zeros(self.slots, np.int32)),
                to_tensor(np.zeros(self.slots, bool)),
                to_tensor(np.zeros(self.slots, np.float32)),
                self._poison_zero,
                self._key,
                to_tensor(np.zeros((self.slots, self.pages_per_seq), np.int32)),
                zero_ads,
            )
            if self._spec_on:
                # the one extra executable speculation buys: all-inactive
                # rows aim every window write at scratch page 0
                _, _, _, _, self._key = self._verify_fn(
                    to_tensor(np.zeros((self.slots, self.spec_k + 1), np.int32)),
                    to_tensor(np.zeros(self.slots, np.int32)),
                    to_tensor(np.zeros(self.slots, bool)),
                    to_tensor(np.ones(self.slots, np.int32)),
                    to_tensor(np.zeros(self.slots, np.float32)),
                    self._poison_zero,
                    self._key,
                    to_tensor(
                        np.zeros((self.slots, self.pages_per_seq), np.int32)
                    ),
                    zero_ads,
                )
            with self._mu:
                self._warm_buckets = set(self.prefill_buckets)
            self._warmed = True
            return self
        for b in self.prefill_buckets:
            _, self._key = self._prefill_fn(
                to_tensor(np.zeros((1, b), np.int32)),
                to_tensor(np.int32(0)), to_tensor(np.int32(b)),
                to_tensor(np.float32(0.0)), self._key,
            )
        _, _, _, self._key = self._decode_fn(
            to_tensor(np.zeros((self.slots, 1), np.int32)),
            to_tensor(np.zeros(self.slots, np.int32)),
            to_tensor(np.zeros(self.slots, bool)),
            to_tensor(np.zeros(self.slots, np.float32)),
            self._poison_zero,
            self._key,
        )
        with self._mu:
            self._warm_buckets = set(self.prefill_buckets)
        self._warmed = True
        return self

    def compile_counts(self):
        """{prefill, decode} trace counts + AOT snapshot hits — the test
        contract is prefill == len(buckets used) and decode == 1, forever
        (engine restarts included: restart rebinds the same executables).
        Paged engines add chunk_prefill (== buckets warmed) and copy (== 1):
        prefix-cache hits and COW copies ride those executables with zero
        fresh traces.  Speculation adds verify (== 1): acceptance churn is
        data, the [slots, k+1] shape never changes."""
        out = {
            "prefill": self._prefill_fn.trace_count,
            "decode": self._decode_fn.trace_count,
            "aot_hits": self._prefill_fn.aot_hits + self._decode_fn.aot_hits,
        }
        if self.paged:
            out["chunk_prefill"] = self._chunk_fn.trace_count
            out["copy"] = self._copy_fn.trace_count
            out["aot_hits"] += self._chunk_fn.aot_hits + self._copy_fn.aot_hits
        if self._import_fn is not None:
            # decode role only (ISSUE 19): the handoff import scatter is one
            # executable forever — payload churn is data
            out["import"] = self._import_fn.trace_count
            out["aot_hits"] += self._import_fn.aot_hits
        if self._spec_on:
            out["verify"] = self._verify_fn.trace_count
            out["aot_hits"] += self._verify_fn.aot_hits
        return out

    @property
    def active_slots(self):
        return sum(1 for r in self._slot_req if r is not None)

    @property
    def pending(self):
        return self._queue.qsize() + len(self._requeue)

    def has_work(self):
        """True when anything is queued, being admitted, or decoding."""
        return bool(
            self._queue.qsize() or self._requeue or self._admitting is not None
            or self.active_slots
        )

    def estimate_drain_s(self):
        """Rough wall seconds until the current backlog drains: tokens still
        owed to active slots plus tokens requested by queued work, decoded
        `slots` at a time at the EWMA decode-round wall time, scaled by the
        EWMA tokens-per-step (speculative steps emit accepted runs — pricing
        them at 1 token/step would over-reject deadlines and mis-rank this
        replica in least-loaded routing).  0 before any traffic (no
        evidence, admit everything) — feeds deadline-aware admission and
        the Retry-After header on 503s."""
        ew = self._step_ewma_s
        if not ew:
            return 0.0
        with self._mu:
            active = sum(
                max(0, r.max_new_tokens - len(r.tokens))
                for r in self._slot_req if r is not None
            )
            queued = max(0, self._queued_new_tokens)
        if not (active or queued):
            return 0.0
        rate = max(1e-6, self._tok_rate_ewma)
        return math.ceil((active + queued) / (max(1, self.slots) * rate)) * ew

    def _shed_retry_after(self, deadline_s):
        """Retry-After for a QueueFull shed: the drain estimate, clamped by
        the request's own deadline — a client must never be told to retry
        after its deadline has already passed.  (DeadlineUnattainable keeps
        the raw estimate on purpose: there the whole point is telling the
        client WHEN the backlog clears, which is past its deadline.)"""
        est = self.estimate_drain_s()
        if deadline_s is not None:
            return min(est, float(deadline_s))
        return est

    def healthz(self):
        """Liveness/readiness snapshot for serve()'s /healthz: live (engine
        exists, scheduler not running), ready (scheduler thread alive),
        draining, or dead (restart budget exhausted) — plus occupancy,
        queue depth, restart count, and the queue-drain estimate.  Also
        carries the load signals a fleet router needs to pick a replica:
        page-pool free fraction (dense engines report free slot fraction),
        prefix-cache size, and the EWMA decode-round wall time."""
        t = self._thread
        if self._dead:
            status = "dead"
        elif self._draining:
            status = "draining"
        elif t is not None and t.is_alive():
            status = "ready"
        else:
            status = "live"
        if self.paged:
            usable = max(1, self._pool.usable_pages)
            # live reservations are spoken-for headroom: the router's
            # decode-side scoring must see pages a pending handoff will
            # consume as already gone, or it over-admits into the gap
            page_free = max(
                0, self._pool.free_count() - self._reserved_pages
            ) / usable
        else:
            page_free = (self.slots - self.active_slots) / self.slots
        ew = self._step_ewma_s
        out = {
            "status": status,
            "slots": self.slots,
            "active_slots": self.active_slots,
            "occupancy": self.active_slots / self.slots,
            "queue_depth": self.pending,
            "restarts": self.restart_count,
            "drain_estimate_s": round(self.estimate_drain_s(), 3),
            "page_free_frac": round(page_free, 4),
            "prefix_cache_size": len(self._prefix) if self._prefix is not None else 0,
            "decode_ewma_ms": round(ew * 1e3, 3) if ew else 0.0,
            # observed mean emitted tokens per slot-step (1.0 unless
            # speculation is accepting drafts) — the factor decode_ewma_ms
            # must be divided by when comparing replica throughput
            "tokens_per_step": round(self._tok_rate_ewma, 3),
            # deadline-miss-rate EWMA over terminal resolutions (ISSUE 16):
            # always present (0.0 before any traffic) so the scrape surface
            # and the autoscaler's pressure signal are shape-stable
            "deadline_miss_rate": round(self._miss_ewma, 4),
            # KV storage precision (ISSUE 18): 'int8' replicas pack ~2x the
            # pages into the same HBM — page_free_frac stays a FRACTION of
            # this replica's own usable pages, so router scoring needs no
            # mode awareness
            "kv_quant": self.kv_quant,
            # disaggregated serving (ISSUE 19): the fleet role this replica
            # plays, plus the pages currently spoken for by un-consumed
            # handoff reservations — the router's pair-pick reads both
            "role": self.role,
            "reserved_pages": int(self._reserved_pages),
            # mesh topology (ISSUE 14/20): degrees + axis shape so a fleet
            # operator can see TP- and CP-sharded replicas from /healthz
            "tp": self.tp,
            "cp": self.cp,
            "mesh_shape": (
                {a: int(s) for a, s in self._mesh.shape.items() if int(s) > 1}
                if self._mesh is not None else {}
            ),
        }
        if self.cp > 1:
            # per-shard free pages: the router's long-context scoring needs
            # the WORST shard (a sequence page can only land on its own
            # shard), not the flattering pool-wide sum
            out["page_free_by_shard"] = [
                int(self._pool.free_count(sh)) for sh in range(self.cp)
            ]
        if self._sessions is not None:
            # session KV residency (ISSUE 20): the router's session
            # pinning and the paddle_session_* metrics families read these
            out["sessions"] = self._sessions.stats()
        if self._lora is not None:
            # adapter residency for the router: a replica already holding a
            # request's adapter skips the load stall — least-loaded scoring
            # prefers it
            lora = dict(self._lora.stats())
            lora["adapters"] = self._lora.resident()
            out["lora"] = lora
        return out

    # -- disaggregated handoff: page reservations (ISSUE 19) -----------------

    def reserve_pages(self, prompt_len, max_new_tokens, ttl_s=None):
        """Reserve decode-side page headroom for a handoff BEFORE prefill
        starts, so a finished prefill can never strand with nowhere to
        land.  Returns {"reservation", "pages", "ttl_s"}; raises QueueFull
        (503 family) when the worst-case page need exceeds the current
        fresh headroom.  The hold is a counter against admission headroom —
        it pins no specific pages and takes no pool refs — and it expires
        after `ttl_s` (FLAGS_serve_reserve_ttl_s default): a router that
        dies mid-handoff just lets the TTL return the headroom."""
        if not self.paged:
            raise EngineUnavailable(
                "page reservations require the paged engine"
            )
        if self._dead:
            raise EngineUnavailable(
                "engine is dead (restart budget exhausted); restart the server"
            )
        if self._draining:
            raise EngineUnavailable(
                "engine is draining (shutdown in progress)",
                retry_after_s=self.estimate_drain_s(),
            )
        need = self._pages_for(int(prompt_len), int(max_new_tokens))
        ttl = float(
            _fcore.flag("FLAGS_serve_reserve_ttl_s") if ttl_s is None
            else ttl_s
        )
        with self._mu:
            self._purge_reservations_locked()
            if need > self._page_fresh_headroom_locked(()):
                raise QueueFull(
                    f"cannot reserve {need} KV pages (prompt {prompt_len} + "
                    f"max_new {max_new_tokens}): only "
                    f"{max(0, self._page_fresh_headroom_locked(()))} "
                    "unreserved pages of headroom",
                    retry_after_s=self.estimate_drain_s(),
                )
            rid = f"rsv-{next(self._rsv_ids)}"
            self._reserved[rid] = (need, time.perf_counter() + ttl)
            self._reserved_pages += need
        _flight.record("disagg", "reserve", rid=rid, pages=need)
        return {"reservation": rid, "pages": int(need), "ttl_s": ttl}

    _rsv_ids = itertools.count(1)  # reservation ids unique across engines

    def _purge_reservations_locked(self, now=None):
        """Drop expired reservations, returning their headroom.  Caller
        holds _mu."""
        if not self._reserved:
            return
        now = time.perf_counter() if now is None else now
        for rid in [r for r, (_, exp) in self._reserved.items() if exp <= now]:
            n, _exp = self._reserved.pop(rid)
            self._reserved_pages -= n
            _flight.record("disagg", "reserve_expired", rid=rid, pages=n)

    def _consume_reservation_locked(self, rid):
        """Release one reservation (the admission it covered is here, or
        the router abandoned it).  Idempotent — an unknown/expired rid is
        a no-op, the request simply competes for headroom unreserved.
        Caller holds _mu."""
        ent = self._reserved.pop(str(rid), None)
        if ent is None:
            return False
        self._reserved_pages -= ent[0]
        return True

    # -- scheduler ----------------------------------------------------------

    def step(self, gen=None):
        """One scheduling tick: evict expired/cancelled slots, admit queued
        requests into free slots (bucketed prefill), then advance every
        active slot one token.  Returns the number of tokens emitted
        (prefill first-tokens included).  Synchronous alternative to
        start() — never mix the two."""
        gen = self._gen if gen is None else gen
        # after warmup() the whole tick is a steady-state region: every
        # compiled body is traced, so any fresh trace/eager compile — and
        # any host sync outside the declared flush boundaries — is a
        # sanitizer finding attributed to the line that caused it
        ctx = (
            _san.steady_state("serving.engine.step")
            if self._warmed and _san.enabled()
            else contextlib.nullcontext()
        )
        with ctx:
            self._evict_expired(gen)
            emitted = self._admit(gen)
            n = emitted + self._decode_once(gen)
        if _fcore.flag("FLAGS_serve_debug_invariants"):
            self._check_invariants()
        # analysis: allow GRAFT010 — liveness stamp: a raced write only delays the watchdog one tick
        self._last_progress = time.monotonic()
        return n

    def run_until_idle(self):
        """Drive step() until queue and slots are empty (synchronous mode)."""
        total = 0
        while not self._dead and self.has_work():
            total += self.step()
        return total

    def start(self):
        """Run the scheduler on a daemon thread (serve() calls this)."""
        if self._thread is not None:
            return self
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="cb-engine", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout=30.0):
        """Stop the scheduler (bounded join) and flush pending host token
        fetches, so a stop racing an in-flight decode cannot leave
        dispatched tokens unemitted or `on_token` callbacks unfired."""
        t = self._thread
        if t is not None:
            self._stop = True
            with self._cv:
                self._cv.notify_all()
            t.join(timeout)
            if t.is_alive():
                # wedged mid-dispatch: abandon it behind the generation fence
                logger.error(
                    "engine scheduler did not stop within %.1fs; abandoning "
                    "the thread", timeout,
                )
                with self._mu:
                    self._gen += 1
            self._thread = None
        with self._mu:
            try:
                self._flush_pending_locked()
            except _StaleEngine:
                pass
            except Exception:
                logger.exception("engine stop: pending-token flush failed")

    def drain(self):
        """Stop admitting (submit raises EngineUnavailable / serve() sheds
        with 503 + Retry-After); in-flight work keeps decoding."""
        self._draining = True
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def __del__(self):
        try:
            t = self._thread
            if t is not None:
                self._stop = True
                with self._cv:
                    self._cv.notify_all()
                t.join(timeout=1.0)
        except Exception:
            pass

    # -- fault domain: restart / fail-all ------------------------------------

    def _on_watchdog(self, region, elapsed):
        # recorded, not fatal: serving restarts the ENGINE, not the process;
        # the EngineSupervisor polls this trip into a bounded warm restart
        self._watchdog_trip = (region, elapsed)

    def _wd_timeout(self):
        return float(_fcore.flag("FLAGS_serve_step_timeout_sec"))

    def _check_gen(self, gen):
        if gen != self._gen:
            raise _StaleEngine(
                f"scheduler generation {gen} superseded by {self._gen}"
            )

    def restart(self, reason=""):
        """Bounded warm restart (EngineSupervisor calls this): abandon the
        possibly-wedged scheduler thread behind the generation fence,
        resolve every in-flight request exactly once — re-queued for
        re-prefill when it emitted no tokens yet, failed with the typed
        EngineRestarted error when its stream already started — and start a
        fresh scheduler bound to the SAME compiled executables and KV pool
        (0 fresh compiles; the pool needs no scrub, garbage rows are never
        attended)."""
        from .. import profiler as _prof

        # a thread wedged inside the armed fetch region may hold _mu; after
        # a bounded wait we proceed anyway — the generation fence makes the
        # stale thread drop its results instead of corrupting the new life
        locked = self._mu.acquire(timeout=1.0)
        try:
            self._gen += 1
            old, self._thread = self._thread, None
            was_threaded = old is not None
            requeue, fail = [], []
            adm, self._admitting = self._admitting, None
            if adm is not None and not adm.finished.is_set():
                (requeue if not adm.tokens else fail).append(adm)
            for s in range(self.slots):
                req = self._slot_req[s]
                self._slot_req[s] = None
                if req is None or req.finished.is_set():
                    continue
                (requeue if not req.tokens else fail).append(req)
            if self.paged:
                # warm restart keeps the POOL and the PREFIX CACHE: only the
                # per-slot mappings drop (an admission interrupted mid-
                # dispatch also parked pages here — release those too, its
                # stale thread bails at the generation fence).  Re-queued
                # requests re-prefill and re-hit the cache.
                for s in range(self.slots):
                    self._release_slot_pages_locked(s)
                self._tables_t = None
                if self._lora is not None:
                    # warm restart keeps the ARENA too: binding refs drop
                    # (re-queued requests re-acquire at re-admission) but
                    # residency holds survive — resident adapters stay
                    # uploaded, zero re-loads after the restart
                    for req in requeue:
                        self._release_adapter_locked(req)
                    for req in fail:
                        self._release_adapter_locked(req)
                    self._slot_adapter[:] = 0
                    self._adapters_t = None
            self._pos[:] = 0
            self._last_tok[:] = 0
            self._temps[:] = 0.0
            # drafters rebuild cleanly at re-admission (reset from prompt +
            # first token) — stale host n-gram state must not outlive the
            # slot assignment it indexed
            self._drafters = [None] * self.slots
            self._ep = None  # epoch members were restarted; drop, don't record
            self._dev = None
            self._pending_fetch = []
            self._watchdog_trip = None
            self._last_progress = time.monotonic()
            for req in requeue:
                req.state = "queued"
                self._queued_new_tokens += req.max_new_tokens
            self._requeue = requeue + self._requeue
            self.restart_count += 1
        finally:
            if locked:
                self._mu.release()
        for req in fail:
            req.error = EngineRestarted(req.id, reason)
            self._resolve(req, "restarted")
        _inj.record_event("engine", f"restart #{self.restart_count}: {reason}")
        _prof.record_serving_fault("restarts")
        logger.warning(
            "engine restart #%d (%s): %d request(s) re-queued, %d failed "
            "with EngineRestarted", self.restart_count, reason or "?",
            len(requeue), len(fail),
        )
        self._stop = False
        if was_threaded:
            self.start()
        return self

    def fail_all(self, reason=""):
        """Terminal: mark the engine dead (submit raises EngineUnavailable)
        and resolve EVERY pending request — queued, admitting, slotted —
        with the typed EngineRestarted error, exactly once.  Called by the
        EngineSupervisor when the restart budget is spent: clients get
        errors, never hangs."""
        self._dead = True
        pending = []
        locked = self._mu.acquire(timeout=1.0)
        try:
            self._gen += 1  # fence out any wedged scheduler
            self._thread = None
            adm, self._admitting = self._admitting, None
            if adm is not None:
                pending.append(adm)
            for s in range(self.slots):
                if self._slot_req[s] is not None:
                    pending.append(self._slot_req[s])
                    self._slot_req[s] = None
            pending.extend(self._requeue)
            self._requeue = []
            while True:
                try:
                    pending.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            self._queued_new_tokens = 0
            if self.paged:
                for s in range(self.slots):
                    self._release_slot_pages_locked(s)
                self._tables_t = None
                if self._lora is not None:
                    for req in pending:
                        self._release_adapter_locked(req)
                    self._slot_adapter[:] = 0
                    self._adapters_t = None
            self._pos[:] = 0
            self._last_tok[:] = 0
            self._temps[:] = 0.0
            self._drafters = [None] * self.slots
            self._ep = None
            self._dev = None
            self._pending_fetch = []
        finally:
            if locked:
                self._mu.release()
        for req in pending:
            if not req.finished.is_set():
                req.error = EngineRestarted(req.id, reason or "engine dead")
                self._resolve(req, "restarted")
        _inj.record_event("engine", f"fail_all: {reason} ({len(pending)} requests)")
        logger.error(
            "engine dead (%s): %d pending request(s) failed with "
            "EngineRestarted", reason or "?", len(pending),
        )
        return len(pending)

    def _loop(self):
        gen = self._gen
        while not self._stop and gen == self._gen:
            self._last_progress = time.monotonic()
            if not self.has_work():
                with self._cv:
                    if (
                        not self._stop
                        and not self._queue.qsize()
                        and not self._requeue
                    ):
                        self._cv.wait(timeout=0.05)
                continue
            try:
                _inj.inject("serve.loop.crash", context="scheduler loop")
                self.step(gen=gen)
            except _StaleEngine:
                return  # a restart superseded this thread
            except _inj.InjectedFault as e:
                # chaos drill: the scheduler thread dies (loudly, but not as
                # an unhandled thread exception); the supervisor sees a dead
                # thread and restarts the engine
                logger.error("engine scheduler crashed: %s", e)
                return
            except Exception as e:  # poison every in-flight request, keep serving
                with self._mu:
                    if gen != self._gen:
                        return
                    self._pending_fetch.clear()
                    for s, req in enumerate(self._slot_req):
                        if req is not None:
                            req.error = e
                            self._finish(s, req, "error")

    # -- internals ----------------------------------------------------------

    @contextlib.contextmanager
    def _bucket_growth(self, bucket):
        """Sanctioned fresh trace: an over-bucket prompt grew a new prefill
        bucket after warmup (one extra compile by design, then cached like
        any other).  Declares the dispatch allowed to the sanitizer and
        marks the bucket warmed once it lands."""
        if not self._warmed or bucket in self._warm_buckets:
            yield
            return
        with _san.allow(f"prefill bucket growth to {bucket}"):
            yield
        with self._mu:
            self._warm_buckets.add(bucket)

    def _bucket_for(self, n):
        for b in self.prefill_buckets:
            if n <= b:
                return b
        # over-bucket prompt: grow a next-power-of-two bucket (one extra
        # compile, then cached/snapshotted like any other)
        b = min(1 << (n - 1).bit_length(), self.max_len - 1)
        with self._mu:
            self.prefill_buckets.append(b)
            self.prefill_buckets.sort()
        return b

    # -- paged-KV allocator ---------------------------------------------------

    def _pages_for(self, prompt_len, max_new):
        """Worst-case pages a request occupies over its whole lifetime (no
        prefix sharing assumed): its positions span [0, L + max_new')."""
        span = int(prompt_len) + min(int(max_new), self.max_len - int(prompt_len))
        return -(-span // self.page_size)

    def _page_headroom_locked(self):
        """Pages obtainable without touching a live slot's mapping: the free
        list plus every page only the prefix cache still holds (ref == 1 for
        a cache-held page means no slot maps it; repeated leaf eviction can
        always reach it).  Caller holds _mu."""
        return self._page_fresh_headroom_locked(())

    def _page_fresh_headroom_locked(self, exclude):
        """Headroom available for FRESH allocations when the pages in
        `exclude` (a request's matched prefix pages, about to be mapped by
        incref) must stay resident: they cannot be counted as evictable or
        the admission check double-counts them.  Session-pinned cache pages
        (ISSUE 20) still count — the allocator may evict the LRU session to
        reach them, which is exactly the pressure behavior sessions promise.
        Caller holds _mu."""
        free = self._pool.free_count()
        if self._prefix is not None:
            free += sum(
                1 for e in self._prefix.entries()
                if self._pool.refs[e.page] == 1 and e.page not in exclude
            )
        # un-consumed handoff reservations (ISSUE 19) are spoken for: fresh
        # allocations for anyone else must leave them covered.  A handoff
        # admission consumes its own reservation BEFORE this check, so the
        # hold converts into exactly the headroom it promised.
        return free - self._reserved_pages

    def _page_fresh_headroom_by_shard_locked(self, exclude):
        """Per-cp-shard fresh headroom (ISSUE 20): under context parallelism
        sequence page k must come from pool shard k % cp, so admission has
        to cover each shard's demand separately — a pool that is half free
        on shard 0 cannot serve shard 1's pages.  Same evictability rules as
        the scalar check.  Caller holds _mu."""
        free = [self._pool.free_count(sh) for sh in range(self.cp)]
        if self._prefix is not None:
            for e in self._prefix.entries():
                if self._pool.refs[e.page] == 1 and e.page not in exclude:
                    free[self._pool.shard_of(e.page)] += 1
        if self._reserved_pages:
            # reservations are not shard-annotated (disagg roles exclude
            # cp); cover them conservatively against every shard
            r = -(-self._reserved_pages // self.cp)
            free = [f - r for f in free]
        return free

    def _fresh_need_by_shard(self, start, stop):
        """How many fresh pages sequence-page indices [start, stop) demand
        from each cp shard under the round-robin layout (index k -> shard
        k % cp)."""
        out = [0] * self.cp
        for j in range(int(start), int(stop)):
            out[j % self.cp] += 1
        return out

    def _alloc_page_locked(self, shard=0):
        """One fresh page from cp shard `shard`, evicting LRU prefix-cache
        entries — and, when every evictable entry on the shard is
        session-pinned, whole LRU sessions (ISSUE 20) — under pressure.
        Only called after the admission headroom check covered the request,
        so the eviction loop terminates with a page.  Caller holds _mu."""
        from .. import profiler as _prof

        while self._pool.free_count(shard) == 0:
            if self._prefix is not None and self._prefix.evict_one(
                self._pool, shard=shard if self.cp > 1 else None
            ) is not None:
                _prof.record_paging_event("cache_evictions")
                continue
            if (
                self._sessions is not None
                and self._sessions.evict_lru() is not None
            ):
                # the evicted session's pins dropped: its chain entries are
                # now ordinary LRU-evictable cache entries — loop back into
                # evict_one to actually free a page on this shard
                _prof.record_paging_event("session_evictions")
                _prof.record_session_stats(self._sessions.stats())
                _flight.record("session", "evicted_for_pages", shard=shard)
                continue
            raise RuntimeError(
                "KV page pool exhausted mid-admission — the headroom "
                "check should have deferred this request (accounting bug)"
            )
        return self._pool.alloc(shard)

    def _release_slot_pages_locked(self, s):
        """Drop slot `s`'s page mappings (finish/evict/restart): every mapped
        page holds one ref for the mapping — shared prefix pages stay alive
        through the cache's own hold.  Caller holds _mu."""
        for p in self._slot_pages[s]:
            self._pool.decref(p)
        self._slot_pages[s] = []
        self._page_table[s, :] = 0

    # -- LoRA adapter bindings ------------------------------------------------

    @staticmethod
    def _req_adapter_id(req):
        """STABLE registry id for prefix-cache keying (0 = base).  Never the
        arena slot — slots are recycled across adapters, ids are not."""
        return 0 if req.adapter is None else req.adapter.adapter_id

    def _release_adapter_locked(self, req):
        """Drop the request's arena binding ref (residency survives — the
        adapter stays warm for the next request).  Idempotent; caller holds
        _mu."""
        slot = req.adapter_slot
        req.adapter_slot = None
        if slot:
            self._lora.release(slot)

    def _evict_expired(self, gen):
        """Evict cancelled/deadline-expired slots at step granularity: flush
        the tokens already dispatched, then recycle the slot (no recompile)
        and resolve the request with its typed error."""
        with self._mu:
            self._check_gen(gen)
            now = time.perf_counter()
            if self.paged:
                self._purge_reservations_locked(now)
            victims = []
            for s, req in enumerate(self._slot_req):
                if req is None:
                    continue
                if req.cancelled:
                    victims.append((s, req, "cancelled"))
                elif req.expired(now):
                    victims.append((s, req, "timeout"))
            if not victims:
                return
            self._flush_pending_locked()  # emit what was already dispatched
            for s, req, reason in victims:
                if self._slot_req[s] is not req:
                    continue  # resolved during the flush (eos/length/nan)
                if reason == "cancelled":
                    req.error = RequestCancelled(req.id, len(req.tokens))
                else:
                    req.error = DeadlineExceeded(
                        req.id, len(req.tokens), req.max_new_tokens,
                        req.deadline_s,
                    )
                self._finish(s, req, reason)

    def _pop_request(self):
        """Next admissible request (restart-requeued work first), resolving
        dead-on-arrival entries (cancelled / already past deadline) without
        burning a prefill.  Caller holds _mu."""
        while True:
            if self._requeue:
                req = self._requeue.pop(0)
            else:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    return None
            self._queued_new_tokens -= req.max_new_tokens
            if req.finished.is_set():
                continue
            if req.cancelled:
                req.error = RequestCancelled(req.id, 0)
                self._resolve(req, "cancelled")
                continue
            if req.expired():
                req.error = DeadlineExceeded(
                    req.id, 0, req.max_new_tokens, req.deadline_s
                )
                self._resolve(req, "timeout")
                continue
            return req

    def _admit(self, gen):
        emitted = 0
        for s in range(self.slots):
            with self._mu:
                self._check_gen(gen)
                if self._slot_req[s] is not None:
                    continue
                req = self._pop_request()
                if req is None:
                    break
                if self.paged:
                    # a handoff admission consumes its reservation FIRST:
                    # inside this same critical section the returned
                    # headroom flows straight into the check below, so the
                    # hold converts into the pages it promised (ISSUE 19)
                    if req.reservation is not None:
                        self._consume_reservation_locked(req.reservation)
                        req.reservation = None
                    # prefix-aware admission: pages a cache hit will map by
                    # incref cost no fresh allocation, so only the unshared
                    # remainder counts against headroom — this is what lets
                    # shared-prefix traffic pack >|dense slots| concurrent
                    # sequences into the same page budget.  Safe to check
                    # here and act in _prefill_into_paged: this scheduler
                    # thread is the only inserter/evictor, so the match
                    # cannot shrink in between.  Matched pages are excluded
                    # from the evictable count — they are about to be pinned.
                    # Handoff imports always land ALL pages fresh (they
                    # commit to the cache after, so future prompts share).
                    coverage = self._pages_for(
                        req.prompt.size, req.max_new_tokens
                    )
                    need = coverage
                    exclude = ()
                    if self._prefix is not None and req.handoff is None:
                        m, fulls, tail, _rows = self._prefix.lookup(
                            req.prompt, adapter=self._req_adapter_id(req)
                        )
                        if m >= self.min_prefix_match:
                            need -= len(fulls)
                            exclude = set(fulls)
                            if tail is not None:
                                exclude.add(tail)
                    if self.cp > 1:
                        # per-shard admission (ISSUE 20): fresh pages land at
                        # sequence indices [coverage - need, coverage), shard
                        # k % cp each — every shard must cover its slice
                        head = self._page_fresh_headroom_by_shard_locked(
                            exclude
                        )
                        by_shard = self._fresh_need_by_shard(
                            coverage - need, coverage
                        )
                        short = any(
                            n > h for n, h in zip(by_shard, head)
                        )
                    else:
                        short = need > self._page_fresh_headroom_locked(
                            exclude
                        )
                    if short:
                        # page pressure: park the request at the head of the
                        # line (FIFO preserved) until draining slots release
                        # enough pages — submit guaranteed need <= pool, so
                        # progress is certain
                        self._requeue.insert(0, req)
                        self._queued_new_tokens += req.max_new_tokens
                        break
                    if req.adapter is not None:
                        # arena admission AFTER the page check, so a parked
                        # request never sits in the queue holding a binding
                        from ..lora.arena import AdapterArenaFull

                        try:
                            req.adapter_slot = self._lora.acquire(req.adapter)
                        except AdapterArenaFull:
                            # every arena slot is pinned by in-flight work:
                            # park exactly like page pressure — a finishing
                            # request's release unblocks us
                            self._requeue.insert(0, req)
                            self._queued_new_tokens += req.max_new_tokens
                            break
                self._admitting = req
                req.state = "prefilling"
            try:
                self._prefill_into(s, req, gen)
                emitted += 1
            except _StaleEngine:
                raise  # the restart now owns this request — hands off
            except Exception as e:  # fail THIS request, keep the engine alive
                req.error = e
                with self._mu:
                    if self._slot_req[s] is req:
                        self._finish(s, req, "error")
                    else:
                        if self.paged and gen == self._gen:
                            # the prefill died after mapping pages but before
                            # the slot landed — unmap them (a restart raced
                            # ahead releases them itself) and drop the
                            # adapter binding the admission took
                            self._release_slot_pages_locked(s)
                            self._release_adapter_locked(req)
                        self._resolve(req, "error")
            finally:
                with self._mu:
                    if self._admitting is req:
                        self._admitting = None
        return emitted

    def _prefill_into(self, s, req, gen):
        if self.paged and req.handoff is not None:
            return self._import_into_paged(s, req, gen)
        if self.paged:
            return self._prefill_into_paged(s, req, gen)
        from .. import to_tensor

        with self._mu:
            self._check_gen(gen)
            # the rebuild after this membership change reads _last_tok — it
            # must reflect every step already dispatched
            self._flush_pending_locked()
            key = self._key
        L = int(req.prompt.size)
        bucket = self._bucket_for(L)
        t_pf = time.perf_counter()
        if req.trace:
            _obs.record("engine.queue", req.trace[0], t0=req._submit_t,
                        t1=t_pf, parent_id=req.trace[1], req=req.id)
        # cache rows run out at max_len: the last writable decode row is
        # max_len - 1, giving max_len - L generatable tokens
        req.max_new_tokens = min(req.max_new_tokens, self.max_len - L)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :L] = req.prompt
        # dispatch OUTSIDE the mutex: the armed region (and the injected
        # hang standing in for a wedged device) must not block submitters
        # or a restart
        with self._watchdog.arm(
            "serve.prefill", timeout=self._wd_timeout(), context=f"req {req.id}"
        ):
            _inj.inject_hang("serve.prefill.hang", context=f"req {req.id}")
            # a restart during the hang owns this request now — bail before
            # dispatching a zombie prefill into the (shared) KV pool
            self._check_gen(gen)
            with self._bucket_growth(bucket):
                nxt, key = self._prefill_fn(
                    to_tensor(toks), to_tensor(np.int32(s)), to_tensor(np.int32(L)),
                    to_tensor(np.float32(req.temperature)), key,
                )
            with _san.allowed_sync("prefill first-token fetch"):
                tok = int(np.asarray(nxt.numpy()).reshape(-1)[0])
        with self._mu:
            self._check_gen(gen)  # a restart while we dispatched owns req now
            self._key = key
            req.ttft_s = time.perf_counter() - req._submit_t
            self._slot_req[s] = req
            self._pos[s] = L
            self._last_tok[s] = tok
            self._temps[s] = req.temperature
            req.state = "decoding"
            self._obs_epoch_close()
            self._dev = None  # membership changed: rebuild device loop state
            self._emit(s, req, tok)
        if req.trace:
            _obs.record("engine.prefill", req.trace[0], t0=t_pf,
                        t1=time.perf_counter(), parent_id=req.trace[1],
                        req=req.id, bucket=bucket, slot=s)

    def _prefill_into_paged(self, s, req, gen):
        """Paged admission: prefix-cache lookup, page mapping (shared fulls
        read-only, COW for a matched partial page, fresh pages for the
        rest), then either a fresh bucketed prefill or a chunk prefill of
        just the unshared suffix — dispatched outside the mutex like the
        dense path.  Commits the prompt's pages to the prefix cache after
        the prefill lands."""
        from .. import profiler as _prof
        from .. import to_tensor

        ps = self.page_size
        L = int(req.prompt.size)
        pinned = None  # COW source, kept alive across our own allocations
        with self._mu:
            self._check_gen(gen)
            self._flush_pending_locked()
            key = self._key
            req.max_new_tokens = min(req.max_new_tokens, self.max_len - L)
            coverage = self._pages_for(L, req.max_new_tokens)
            match_len, shared_full, tail_page, tail_rows = 0, [], None, 0
            if self._prefix is not None:
                m, fp, tp, tr = self._prefix.lookup(
                    req.prompt, adapter=self._req_adapter_id(req)
                )
                if m >= self.min_prefix_match:
                    match_len, shared_full, tail_page, tail_rows = m, fp, tp, tr
                else:
                    tp = None
                if tp is not None and tr > 0:
                    # pin the COW source: allocating fresh pages below may
                    # evict cache entries, and the source must survive until
                    # the copy lands
                    self._pool.incref(tp)
                    pinned = tp
            pages = []
            try:
                for p in shared_full:
                    self._pool.incref(p)
                    pages.append(p)
                # fresh pages go to their sequence index's cp shard (index
                # k -> shard k % cp, shards=1 under no cp) — the round-robin
                # layout the context-parallel decode kernel assumes
                for i in range(len(shared_full), coverage):
                    pages.append(self._alloc_page_locked(i % self.cp))
            except RuntimeError:
                if match_len == 0:
                    raise
                # rare corner (tiny pools): the COW pin itself kept the last
                # evictable page alive.  Fall back to a fresh prefill — the
                # admission headroom check guarantees full coverage without
                # any sharing.
                for p in pages:
                    self._pool.decref(p)
                if pinned is not None:
                    self._pool.decref(pinned)
                    pinned = None
                match_len, shared_full, tail_page, tail_rows = 0, [], None, 0
                pages = [
                    self._alloc_page_locked(i % self.cp)
                    for i in range(coverage)
                ]
            copy_args = None
            if match_len and tail_rows > 0:
                copy_args = (tail_page, pages[len(shared_full)])
            self._page_table[s, :] = 0
            self._page_table[s, : len(pages)] = pages
            self._slot_pages[s] = list(pages)
            _prof.record_prefix_lookup(
                match_len > 0, tokens_saved=match_len,
                cow_copies=1 if copy_args else 0,
            )
            if req.session_id is not None and self._sessions is not None:
                # session accounting (ISSUE 20): every matched prompt token
                # is prefill work the session's pinned chain (or the shared
                # prefix cache) absorbed; bump the session's LRU clock so
                # an active conversation never evicts under its own turns
                req.session_reused_tokens = match_len
                self._sessions.tokens_saved_total += match_len
                self._sessions.touch(req.session_id)
                _prof.record_session_stats(self._sessions.stats())
            row_table = self._page_table[s].copy()
        suffix = L - match_len
        bucket = self._bucket_for(suffix)
        t_pf = time.perf_counter()
        if req.trace:
            _obs.record("engine.queue", req.trace[0], t0=req._submit_t,
                        t1=t_pf, parent_id=req.trace[1], req=req.id)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :suffix] = req.prompt[match_len:]
        try:
            # dispatch OUTSIDE the mutex (same contract as the dense path):
            # the armed region must not block submitters or a restart
            with self._watchdog.arm(
                "serve.prefill", timeout=self._wd_timeout(),
                context=f"req {req.id}",
            ):
                _inj.inject_hang("serve.prefill.hang", context=f"req {req.id}")
                # a restart during the hang owns this request (and released
                # the pages we just mapped) — bail before writing the arena
                self._check_gen(gen)
                if copy_args is not None:
                    self._copy_fn(
                        to_tensor(np.int32(copy_args[0])),
                        to_tensor(np.int32(copy_args[1])),
                    )
                ad_t = to_tensor(
                    np.full(1, req.adapter_slot or 0, np.int32)
                )
                with self._bucket_growth(bucket):
                    if match_len == 0:
                        nxt, key = self._prefill_fn(
                            to_tensor(toks), to_tensor(row_table),
                            to_tensor(np.int32(L)),
                            to_tensor(np.float32(req.temperature)), key,
                            ad_t,
                        )
                    else:
                        nxt, key = self._chunk_fn(
                            to_tensor(toks), to_tensor(row_table),
                            to_tensor(np.int32(suffix)),
                            to_tensor(np.full(1, match_len, np.int32)),
                            to_tensor(np.float32(req.temperature)), key,
                            ad_t,
                        )
                with _san.allowed_sync("prefill first-token fetch"):
                    tok = int(np.asarray(nxt.numpy()).reshape(-1)[0])
        finally:
            if pinned is not None:
                with self._mu:
                    self._pool.decref(pinned)
        with self._mu:
            self._check_gen(gen)  # a restart while we dispatched owns req now
            self._key = key
            if self._prefix is not None:
                inserted = self._prefix.commit(
                    req.prompt, pages, self._pool,
                    adapter=self._req_adapter_id(req),
                )
                if inserted:
                    _prof.record_paging_event("cache_commits", inserted)
            req.ttft_s = time.perf_counter() - req._submit_t
            self._slot_req[s] = req
            self._pos[s] = L
            self._last_tok[s] = tok
            self._temps[s] = req.temperature
            self._slot_adapter[s] = req.adapter_slot or 0
            if self._spec_on and req.temperature == 0.0 and (
                req.spec_k is None or req.spec_k > 0
            ):
                # greedy slots draft from their own history (prompt + first
                # token); sampled slots ride the verify step undrafted —
                # greedy equivalence is the only acceptance rule we prove
                self._drafters[s] = NgramDrafter(self._spec_ngram).reset(
                    [int(t) for t in req.prompt] + [tok]
                )
            else:
                self._drafters[s] = None
            req.state = "decoding"
            self._obs_epoch_close()
            self._dev = None  # membership changed: rebuild device loop state
            self._emit(s, req, tok)
        if req.trace:
            _obs.record(
                "engine.chunk_prefill" if match_len else "engine.prefill",
                req.trace[0], t0=t_pf, t1=time.perf_counter(),
                parent_id=req.trace[1], req=req.id, bucket=bucket, slot=s,
                prefix_match=match_len or None,
                adapter=req.adapter.name if req.adapter is not None else None,
            )

    def _import_into_paged(self, s, req, gen):
        """Disaggregated admission (ISSUE 19): the prompt's KV arrives in
        `req.handoff` instead of being prefilled.  Maps fresh pages, lands
        the shipped rows page-by-page through the compiled import scatter
        (one executable, payload is data), commits the prompt pages to the
        prefix cache so FUTURE identical prompts share them, then seats the
        slot exactly like a prefill landing: pos = L, last_tok = the
        prefill worker's sampled first token.  Greedy continuation is
        bit-identical to a colocated engine at the same seed — same weights
        and identical arena rows leave the decode step nothing to differ
        on."""
        from .. import profiler as _prof
        from .. import to_tensor

        ps = self.page_size
        L = int(req.prompt.size)
        layers, first_tok = req.handoff
        n_prompt_pages = -(-L // ps)
        with self._mu:
            self._check_gen(gen)
            self._flush_pending_locked()
            req.max_new_tokens = min(req.max_new_tokens, self.max_len - L)
            coverage = self._pages_for(L, req.max_new_tokens)
            pages = [
                self._alloc_page_locked(i % self.cp) for i in range(coverage)
            ]
            self._page_table[s, :] = 0
            self._page_table[s, : len(pages)] = pages
            self._slot_pages[s] = list(pages)
        t_pf = time.perf_counter()
        if req.trace:
            _obs.record("engine.queue", req.trace[0], t0=req._submit_t,
                        t1=t_pf, parent_id=req.trace[1], req=req.id)
        nl = len(self._arenas)
        q8 = self.kv_quant == "int8"
        elem = np.dtype(np.int8) if q8 else self._kv_dtype_np
        kvh, hd = self._kv_heads, self._head_dim
        # dispatch OUTSIDE the mutex (same contract as the prefill paths):
        # the armed region must not block submitters or a restart
        with self._watchdog.arm(
            "serve.import", timeout=self._wd_timeout(),
            context=f"req {req.id} ({n_prompt_pages} pages)",
        ):
            # a restart during a wedged import owns this request (and
            # released the pages we just mapped) — bail before writing
            self._check_gen(gen)
            for i in range(n_prompt_pages):
                lo, hi = i * ps, min(L, (i + 1) * ps)
                rows = hi - lo
                kt = np.zeros((nl, ps, kvh, hd), elem)
                vt = np.zeros((nl, ps, kvh, hd), elem)
                for li, ly in enumerate(layers):
                    kt[li, :rows] = ly["k"][lo:hi]
                    vt[li, :rows] = ly["v"][lo:hi]
                args = [to_tensor(kt), to_tensor(vt)]
                if q8:
                    # padding rows carry scale 1.0, never 0: they sit past
                    # the slot's pos and are position-masked, but their
                    # dequantized values still flow through the masked
                    # attention sum and must stay finite
                    kst = np.ones((nl, ps, kvh, 1), np.float32)
                    vst = np.ones((nl, ps, kvh, 1), np.float32)
                    for li, ly in enumerate(layers):
                        kst[li, :rows] = ly["k_scale"][lo:hi]
                        vst[li, :rows] = ly["v_scale"][lo:hi]
                    args += [to_tensor(kst), to_tensor(vst)]
                self._import_fn(*args, to_tensor(np.int32(pages[i])))
        with self._mu:
            self._check_gen(gen)  # a restart while we imported owns req now
            if self._prefix is not None:
                inserted = self._prefix.commit(
                    req.prompt, pages, self._pool,
                    adapter=self._req_adapter_id(req),
                )
                if inserted:
                    _prof.record_paging_event("cache_commits", inserted)
            req.ttft_s = time.perf_counter() - req._submit_t
            self._slot_req[s] = req
            self._pos[s] = L
            self._last_tok[s] = first_tok
            self._temps[s] = req.temperature
            self._slot_adapter[s] = 0  # handoffs never carry an adapter
            if self._spec_on and req.temperature == 0.0 and (
                req.spec_k is None or req.spec_k > 0
            ):
                self._drafters[s] = NgramDrafter(self._spec_ngram).reset(
                    [int(t) for t in req.prompt] + [first_tok]
                )
            else:
                self._drafters[s] = None
            req.state = "decoding"
            req.handoff = None  # the arena owns the rows now; free the copy
            self._obs_epoch_close()
            self._dev = None  # membership changed: rebuild device loop state
            _prof.record_disagg_event("imports")
            _prof.record_disagg_event("import_pages", n_prompt_pages)
            self._emit(s, req, first_tok)
        if req.trace:
            _obs.record(
                "engine.import", req.trace[0], t0=t_pf,
                t1=time.perf_counter(), parent_id=req.trace[1], req=req.id,
                slot=s, pages=n_prompt_pages,
            )

    def _decode_once(self, gen):
        if self._spec_on:
            return self._decode_once_spec(gen)
        from .. import profiler as _prof
        from .. import to_tensor

        with self._mu:
            self._check_gen(gen)
            active_idx = [s for s in range(self.slots) if self._slot_req[s] is not None]
            if not active_idx:
                return 0
            t0 = time.perf_counter()
            if self._dev is None:
                self._obs_epoch_close()
                active = np.zeros(self.slots, bool)
                active[active_idx] = True
                self._dev = (
                    to_tensor(self._last_tok.reshape(self.slots, 1)),
                    to_tensor(self._pos.copy()), to_tensor(active),
                    to_tensor(self._temps.copy()),
                )
                if self.paged:
                    # page tables (and adapter bindings) change exactly when
                    # membership does — the same events that invalidate _dev
                    # — so one H2D mirror per membership change covers every
                    # following step
                    self._tables_t = to_tensor(self._page_table.copy())
                    self._adapters_t = to_tensor(self._slot_adapter.copy())
                self._obs_epoch_open(active_idx)
            toks_t, pos_t, active_t, temps_t = self._dev
            key = self._key
            poison_t, poisoned = self._poison_zero, None
            if _inj.should_fire("serve.decode.nan", context=f"slot {active_idx[0]}"):
                poisoned = active_idx[0]
                pz = np.zeros(self.slots, bool)
                pz[poisoned] = True
                poison_t = to_tensor(pz)
        with self._watchdog.arm(
            "serve.decode", timeout=self._wd_timeout(),
            context=f"{len(active_idx)} active slots",
        ):
            if self.paged:
                nxt, new_pos, finite, key = self._decode_fn(
                    toks_t, pos_t, active_t, temps_t, poison_t, key,
                    self._tables_t, self._adapters_t,
                )
            else:
                nxt, new_pos, finite, key = self._decode_fn(
                    toks_t, pos_t, active_t, temps_t, poison_t, key
                )
        with self._mu:
            self._check_gen(gen)
            self._key = key
            self._dev = (nxt, new_pos, active_t, temps_t)
            for s in active_idx:
                self._pos[s] += 1
            # fetch to host only when something needs the values this step —
            # a per-token consumer (EOS watch, streaming callback), a slot
            # hitting its length bound, or a poisoned step that must be
            # checked now.  Otherwise the step stays in flight and the sync
            # lands at the next membership change, so XLA pipelines decode
            # dispatches exactly like the lock-step loop.
            self._pending_fetch.append((nxt, finite, active_idx, t0))
            depth = len(self._pending_fetch)
            if poisoned is not None or any(
                self._slot_req[s].eos_token_id is not None
                or self._slot_req[s].on_token is not None
                or len(self._slot_req[s].tokens) + depth
                >= self._slot_req[s].max_new_tokens
                for s in active_idx
            ):
                self._flush_pending_locked()
            if self._ep is not None:
                self._ep["ticks"] += 1
            _prof.record_serving_tick(
                len(active_idx) / self.slots, self._queue.qsize(),
                time.perf_counter() - t0,
            )
            if self.paged:
                _prof.record_paging_tick(
                    self._pool.used_count(), self._pool.usable_pages
                )
                if self.kv_quant == "int8":
                    # per-layer work divided out: one KV row-pair quantized
                    # per active slot, every mapped page dequantized in the
                    # kernel's page walk
                    _prof.record_kv_quant_event(
                        "quantize", len(active_idx)
                    )
                    _prof.record_kv_quant_event(
                        "dequantize",
                        sum(len(self._slot_pages[s]) for s in active_idx),
                    )
        return len(active_idx)

    def _decode_once_spec(self, gen):
        """One speculative round for every active slot: draft on the host
        (prompt-lookup, free), verify k+1 positions in ONE compiled dispatch,
        emit the accepted run.  Shapes are fixed at [slots, spec_k+1] —
        draft content, validity, and acceptance are data, so acceptance
        churn and slot churn alike cause zero recompiles.  Unlike the plain
        path this fetches every step (the next draft needs this step's
        accepted tokens on the host); the batching the plain path buys with
        deferred fetches is what speculation replaces — >1 token per sync."""
        from .. import profiler as _prof
        from .. import to_tensor

        K1 = self.spec_k + 1
        with self._mu:
            self._check_gen(gen)
            active_idx = [s for s in range(self.slots) if self._slot_req[s] is not None]
            if not active_idx:
                return 0
            t0 = time.perf_counter()
            if self._dev is None:
                self._obs_epoch_close()
                active = np.zeros(self.slots, bool)
                active[active_idx] = True
                # spec loop state is (pos, active, temps): tokens rebuild
                # host-side every step from _last_tok + fresh drafts
                self._dev = (
                    to_tensor(self._pos.copy()), to_tensor(active),
                    to_tensor(self._temps.copy()),
                )
                self._tables_t = to_tensor(self._page_table.copy())
                self._adapters_t = to_tensor(self._slot_adapter.copy())
                self._obs_epoch_open(active_idx)
            pos_t, active_t, temps_t = self._dev
            key = self._key
            toks = np.zeros((self.slots, K1), np.int32)
            vl = np.ones(self.slots, np.int32)
            proposed = 0
            for s in active_idx:
                req = self._slot_req[s]
                toks[s, 0] = self._last_tok[s]
                dr = self._drafters[s]
                if dr is None:
                    continue  # sampled or spec_k=0 request: plain-decode row
                # the clamp that keeps every COMMITTED row mapped: at most
                # remaining-1 drafts, so n_emit never overshoots the length
                # bound and the last committed row stays < max_len
                budget = min(
                    self.spec_k,
                    self.spec_k if req.spec_k is None else req.spec_k,
                    req.max_new_tokens - len(req.tokens) - 1,
                )
                draft = dr.propose(budget) if budget > 0 else []
                if draft:
                    toks[s, 1:1 + len(draft)] = draft
                    vl[s] = 1 + len(draft)
                    proposed += len(draft)
            if self._ep is not None:
                self._ep["proposed"] += proposed
            poison_t, poisoned = self._poison_zero, None
            if _inj.should_fire("serve.decode.nan", context=f"slot {active_idx[0]}"):
                poisoned = active_idx[0]
                pz = np.zeros(self.slots, bool)
                pz[poisoned] = True
                poison_t = to_tensor(pz)
            toks_t = to_tensor(toks)
            vl_t = to_tensor(vl)
        with self._watchdog.arm(
            "serve.decode", timeout=self._wd_timeout(),
            context=f"{len(active_idx)} active slots (spec k={self.spec_k})",
        ):
            out, n_emit, new_pos, finite, key = self._verify_fn(
                toks_t, pos_t, active_t, vl_t, temps_t, poison_t, key,
                self._tables_t, self._adapters_t,
            )
        with self._mu:
            self._check_gen(gen)
            self._key = key
            self._dev = (new_pos, active_t, temps_t)
            with self._watchdog.arm(
                "serve.fetch", timeout=self._wd_timeout(),
                context=f"verify fetch ({len(active_idx)} slots)",
            ), _san.allowed_sync("speculative verify fetch"):
                out_np = np.asarray(out.numpy())
                n_np = np.asarray(n_emit.numpy()).reshape(-1)
                fin_np = np.asarray(finite.numpy()).reshape(-1)
            # a restart that could not take the mutex may have superseded
            # us mid-fetch — bail before touching the new life's slot table
            self._check_gen(gen)
            now = time.perf_counter()
            per = now - t0
            self._step_ewma_s = (
                per if self._step_ewma_s is None
                else 0.8 * self._step_ewma_s + 0.2 * per
            )
            accepted = 0
            emitted_total = 0
            for s in active_idx:
                req = self._slot_req[s]
                if req is None:
                    continue
                if not fin_np[s]:
                    _prof.record_serving_fault("nonfinite")
                    req.error = NonFiniteLogits(
                        f"request {req.id}: non-finite logit window at "
                        f"position {int(self._pos[s])} (slot {s}); the slot "
                        "was evicted — co-batched requests are unaffected"
                    )
                    self._finish(s, req, "error")
                    continue
                n = int(n_np[s])
                self._pos[s] += n
                accepted += max(0, n - 1)
                emitted_total += n
                dr = self._drafters[s]
                for j in range(n):
                    if self._slot_req[s] is not req:
                        break  # EOS inside the accepted window right-trims
                    tok = int(out_np[s, j])
                    self._last_tok[s] = tok
                    if dr is not None:
                        dr.extend(tok)
                    self._emit(s, req, tok)
            if emitted_total:
                self._tok_rate_ewma = (
                    0.8 * self._tok_rate_ewma
                    + 0.2 * (emitted_total / len(active_idx))
                )
            if self._ep is not None:
                self._ep["ticks"] += 1
                self._ep["accepted"] += accepted
            _prof.record_serving_tick(
                len(active_idx) / self.slots, self._queue.qsize(),
                time.perf_counter() - t0,
            )
            _prof.record_paging_tick(
                self._pool.used_count(), self._pool.usable_pages
            )
            _prof.record_speculation(
                proposed, accepted, emitted_total, len(active_idx)
            )
            if self.kv_quant == "int8":
                # the verify window quantizes k+1 row-pairs per active slot
                _prof.record_kv_quant_event(
                    "quantize", len(active_idx) * K1
                )
                _prof.record_kv_quant_event(
                    "dequantize",
                    sum(len(self._slot_pages[s]) for s in active_idx),
                )
        return len(active_idx)

    def _obs_epoch_open(self, active_idx):
        """Start a decode-epoch summary (caller holds _mu): the stretch of
        constant slot membership that begins at this device-state rebuild.
        Host-side bookkeeping only — a dict, no tensor touches — so it is
        legal inside the sanitizer's steady-state zone."""
        if not _obs.enabled():
            self._ep = None
            return
        members = [(s, self._slot_req[s]) for s in active_idx]
        if not any(r.trace for _, r in members):
            self._ep = None
            return
        self._ep = {
            "t0": time.perf_counter(), "ticks": 0, "members": members,
            # speculation accounting over the epoch (zeros in plain mode)
            "proposed": 0, "accepted": 0,
        }

    def _obs_epoch_close(self):
        """Close the open decode epoch (caller holds _mu): one summarizing
        engine.decode span per traced member request — plus, when
        speculation is on, an engine.verify span carrying the epoch's
        proposed/accepted draft counts (the trace-visible acceptance
        evidence ISSUE 11 requires)."""
        ep, self._ep = self._ep, None
        if not ep or not ep["ticks"]:
            return
        t1 = time.perf_counter()
        for s, req in ep["members"]:
            if req.trace:
                _obs.record(
                    "engine.decode", req.trace[0], t0=ep["t0"], t1=t1,
                    parent_id=req.trace[1], req=req.id, slot=s,
                    ticks=ep["ticks"],
                    adapter=req.adapter.name if req.adapter is not None else None,
                )
                if self._spec_on:
                    _obs.record(
                        "engine.verify", req.trace[0], t0=ep["t0"], t1=t1,
                        parent_id=req.trace[1], req=req.id, slot=s,
                        ticks=ep["ticks"], proposed=ep["proposed"],
                        accepted=ep["accepted"],
                    )

    def _flush_pending_locked(self):
        """Fetch every dispatched-but-unfetched decode step and emit its
        tokens; a slot whose logit window went non-finite errors alone.
        Membership is constant across buffered steps (any change flushes
        first), so each entry's active set is exact.  Caller holds _mu; the
        blocking fetch runs under the serve.fetch watchdog region and
        re-checks the generation after it (a restart that could not take
        the mutex may have superseded us mid-fetch)."""
        from .. import profiler as _prof

        if not self._pending_fetch:
            return
        gen0 = self._gen
        batches, self._pending_fetch = self._pending_fetch, []
        t_f0 = time.perf_counter()
        with self._watchdog.arm(
            "serve.fetch", timeout=self._wd_timeout(),
            context=f"{len(batches)} buffered steps",
        ), _san.allowed_sync("batched decode-token flush"):
            fetched = [
                (
                    np.asarray(nxt.numpy()).reshape(-1),
                    np.asarray(fin.numpy()).reshape(-1),
                    idx,
                    t0,
                )
                for nxt, fin, idx, t0 in batches
            ]
        self._check_gen(gen0)
        now = time.perf_counter()
        if _obs.enabled():
            flushed = {}
            for _nxt, _fin, idx, _t0 in fetched:
                for s in idx:
                    r = self._slot_req[s]
                    if r is not None and r.trace:
                        flushed[r.id] = r
            for r in flushed.values():
                _obs.record("engine.fetch", r.trace[0], t0=t_f0, t1=now,
                            parent_id=r.trace[1], req=r.id,
                            steps=len(fetched))
        # EWMA decode-round wall time: dispatch-to-fetch of this burst over
        # its step count — feeds estimate_drain_s / Retry-After
        per = (now - fetched[0][3]) / len(fetched)
        self._step_ewma_s = (
            per if self._step_ewma_s is None
            else 0.8 * self._step_ewma_s + 0.2 * per
        )
        for nxt_np, fin_np, idx, _t0 in fetched:
            for s in idx:
                req = self._slot_req[s]
                if req is None:  # finished earlier in this flush
                    continue
                if not fin_np[s]:
                    _prof.record_serving_fault("nonfinite")
                    req.error = NonFiniteLogits(
                        f"request {req.id}: non-finite logit window at "
                        f"position {int(self._pos[s])} (slot {s}); the slot "
                        "was evicted — co-batched requests are unaffected"
                    )
                    self._finish(s, req, "error")
                    continue
                tok = int(nxt_np[s])
                self._last_tok[s] = tok
                self._emit(s, req, tok)

    def _emit(self, s, req, tok):
        req.tokens.append(tok)
        if req.on_token is not None:
            try:
                req.on_token(tok)
            except Exception:
                pass  # a broken consumer must not take the engine down
        if req.eos_token_id is not None and tok == req.eos_token_id:
            self._finish(s, req, "eos")
        elif len(req.tokens) >= req.max_new_tokens:
            self._finish(s, req, "length")

    def _finish(self, s, req, reason):
        if (
            self.paged and req.export_kv and req.kv_export is None
            and reason in ("eos", "length")
        ):
            # disaggregated prefill (ISSUE 19): read the committed prompt
            # pages into the handoff payload NOW, while the slot still maps
            # them — one line down they return to the pool
            try:
                self._export_slot_locked(s, req)
            except Exception:
                # the handoff consumer sees kv_export None and fails the
                # hop; the pages must still be released below
                logger.exception(
                    "disagg: page export failed for request %d", req.id
                )
        if (
            self.paged and self._sessions is not None
            and req.session_id is not None and reason in ("eos", "length")
        ):
            # session KV (ISSUE 20): commit + pin the FULL committed
            # sequence (prompt AND generated tokens, truncated to the rows
            # whose KV actually landed) while the slot still maps its pages
            # — turn N+1 chunk-prefills only past this point
            try:
                self._bind_session_locked(s, req)
            except Exception:
                # a failed bind degrades to stateless turn N+1 (re-prefill);
                # never let it take the finish path down with it
                logger.exception(
                    "session: bind failed for request %d (session %r)",
                    req.id, req.session_id,
                )
        # recycle immediately: no cache scrub needed — the slot's next
        # prefill overwrites rows [0, bucket) and decode masks the rest
        self._slot_req[s] = None
        self._pos[s] = 0
        self._last_tok[s] = 0
        self._temps[s] = 0.0
        self._drafters[s] = None
        if self.paged:
            # mappings drop; committed prefix pages live on through the
            # cache's own hold, everything else returns to the free list
            self._release_slot_pages_locked(s)
            if self._lora is not None:
                # the binding ref drops; residency survives, so the adapter
                # stays warm until arena LRU pressure needs its slot
                self._slot_adapter[s] = 0
                self._release_adapter_locked(req)
        self._obs_epoch_close()
        self._dev = None  # membership changed: rebuild device loop state
        self._resolve(req, reason)

    def _bind_session_locked(self, s, req):
        """Commit slot `s`'s committed rows to the prefix cache and (re)bind
        the request's session to the covering chain (ISSUE 20).  The
        committed sequence is concat(prompt, generated)[:pos] — the engine's
        decode invariant is that KV rows [0, pos) hold exactly those tokens;
        the LAST emitted token's KV is never written (it would land at row
        pos on the next step), so it is excluded and turn N+1's chunk
        prefill recomputes it at its true rope offset.  Caller holds _mu,
        slot still maps its pages."""
        from .. import profiler as _prof

        pos = int(self._pos[s])
        seq = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)]
        )[:pos]
        if seq.size == 0:
            return
        ad = self._req_adapter_id(req)
        inserted = self._prefix.commit(
            seq, self._slot_pages[s], self._pool, adapter=ad
        )
        if inserted:
            _prof.record_paging_event("cache_commits", inserted)
        entries, covered = self._prefix.chain(seq, adapter=ad)
        evicted = self._sessions.bind(
            req.session_id, seq, entries, adapter=ad
        )
        if evicted:
            _prof.record_paging_event("session_evictions", len(evicted))
        _prof.record_session_stats(self._sessions.stats())
        _flight.record(
            "session", "bind", req=req.id, sid=req.session_id,
            tokens=int(seq.size), pages=len(entries), covered=int(covered),
            turns=self._sessions.get(req.session_id)["turns"],
        )

    def _export_slot_locked(self, s, req):
        """Read slot `s`'s committed prompt rows — [0, L) of every layer's
        K/V through its page mapping — into a serialized handoff payload on
        `req.kv_export` (ISSUE 19).  Exactly the rows a colocated engine
        would hold after this prompt's prefill: the first generated token's
        KV is NOT yet written (it lands when the decode side feeds it back
        at position L), so export-at-finish of a max_new_tokens=1 prefill
        is the complete, sufficient handoff.  Rows ship as stored (int8 +
        scale rows under kv_quant='int8').  Caller holds _mu."""
        from .. import profiler as _prof
        from .paging import serialize_kv_handoff

        ps = self.page_size
        L = int(req.prompt.size)
        n_pages = -(-L // ps)
        idx = np.asarray(self._slot_pages[s][:n_pages], np.int64)
        layers = []
        with _san.allowed_sync("disagg page export"):
            for a in self._arenas:
                ly = {
                    "k": np.asarray(a.k.numpy())[idx].reshape(
                        n_pages * ps, self._kv_heads, self._head_dim
                    )[:L],
                    "v": np.asarray(a.v.numpy())[idx].reshape(
                        n_pages * ps, self._kv_heads, self._head_dim
                    )[:L],
                }
                if a.k_scale is not None:
                    ly["k_scale"] = np.asarray(a.k_scale.numpy())[idx].reshape(
                        n_pages * ps, self._kv_heads, 1
                    )[:L]
                    ly["v_scale"] = np.asarray(a.v_scale.numpy())[idx].reshape(
                        n_pages * ps, self._kv_heads, 1
                    )[:L]
                layers.append(ly)
        payload = serialize_kv_handoff(
            layers, L, self.kv_quant, self._kv_dtype_np.name
        )
        payload["first_token"] = int(req.tokens[0]) if req.tokens else None
        req.kv_export = payload
        _prof.record_disagg_event("exports")
        _prof.record_disagg_event("handoff_bytes", payload["payload_bytes"])
        _flight.record(
            "disagg", "export", req=req.id, pages=n_pages,
            bytes=payload["payload_bytes"],
        )

    def _resolve(self, req, reason):
        """Terminal transition, exactly once: a request that already
        resolved (restart raced an eviction, stop raced a finish) is left
        untouched — never double-completed, never silently lost."""
        from .. import profiler as _prof

        if req.finished.is_set():
            return
        req.finish_reason = reason
        req.state = reason
        req._finish_t = time.perf_counter()
        if reason in ("eos", "length"):
            _prof.record_serving_request(
                req.ttft_s or 0.0, len(req.tokens),
                req._finish_t - req._submit_t,
            )
        elif reason == "timeout":
            _prof.record_serving_fault("deadline_miss")
        if reason in ("eos", "length", "timeout"):
            # miss-rate EWMA over ORGANIC terminal outcomes only — restarts
            # and cancellations are not deadline signal; _mu (reentrant)
            # covers resolution from both the scheduler thread and the
            # stop/fail_all paths; mirrored into the profiler gauge so
            # /metrics scrapes the same number /healthz reports
            with self._mu:
                self._miss_ewma = (
                    (1.0 - _MISS_EWMA_ALPHA) * self._miss_ewma
                    + _MISS_EWMA_ALPHA * (1.0 if reason == "timeout" else 0.0)
                )
                rate = self._miss_ewma
            _prof.record_deadline_miss_rate(rate)
        elif reason == "cancelled":
            _prof.record_serving_fault("cancelled")
        elif reason == "restarted":
            _prof.record_serving_fault("restarted_requests")
        req.finished.set()

    # -- debug invariants ----------------------------------------------------

    def _check_invariants(self):
        """FLAGS_serve_debug_invariants: loud failure instead of a silent
        slot leak.  After a step: a free slot is fully recycled (pos,
        last_tok, temps zeroed), an occupied slot holds exactly one LIVE
        request at a position within the cache, and no request occupies two
        slots."""
        with self._mu:
            seen = {}
            for s, req in enumerate(self._slot_req):
                if req is None:
                    if self._pos[s] != 0 or self._temps[s] != 0.0:
                        raise AssertionError(
                            f"slot invariant: slot {s} is free but not "
                            f"recycled (pos={int(self._pos[s])}, "
                            f"temp={float(self._temps[s])})"
                        )
                    continue
                if req.finished.is_set():
                    raise AssertionError(
                        f"slot invariant: slot {s} holds already-resolved "
                        f"request {req.id} ({req.finish_reason})"
                    )
                if id(req) in seen:
                    raise AssertionError(
                        f"slot invariant: request {req.id} occupies slots "
                        f"{seen[id(req)]} and {s}"
                    )
                seen[id(req)] = s
                if not 0 < int(self._pos[s]) <= self.max_len:
                    raise AssertionError(
                        f"slot invariant: slot {s} (request {req.id}) at "
                        f"position {int(self._pos[s])} outside (0, "
                        f"{self.max_len}]"
                    )
            if self._queued_new_tokens < 0:
                raise AssertionError(
                    "slot invariant: queued-token accounting went negative "
                    f"({self._queued_new_tokens})"
                )
            if self.paged:
                self._check_page_invariants_locked()
            if self._lora is not None:
                bindings = {}
                for s in range(self.slots):
                    a = int(self._slot_adapter[s])
                    if self._slot_req[s] is None:
                        if a:
                            raise AssertionError(
                                f"lora invariant: free slot {s} still bound "
                                f"to arena slot {a}"
                            )
                        continue
                    if a:
                        bindings[a] = bindings.get(a, 0) + 1
                self._lora.check_invariants(bindings)

    def _check_page_invariants_locked(self):
        """FLAGS_serve_debug_invariants, paged extension: every page's
        refcount equals its observable holds (slot mappings + prefix-cache
        entries), the free list is exactly the ref-0 pages, free slots map
        nothing, and an occupied slot's table covers every position it has
        written.  Caller holds _mu."""
        pool, ps = self._pool, self.page_size
        check_table_bounds(self._page_table, pool.num_pages)
        # ISSUE 18: the scale arenas are audited alongside the K/V pages —
        # congruence (same page count, [ps, kv_heads, 1] f32 rows) is the
        # whole refcount story, because page p's scale rows share page p's
        # refcount by construction
        check_scale_arenas(self._arenas, pool.num_pages, ps)
        expected = np.zeros(pool.num_pages, np.int64)
        for p in pool.scratch_pages:
            expected[p] = 1  # scratch pin (one per cp shard, ISSUE 20)
        for s in range(self.slots):
            row = self._page_table[s]
            mapped = self._slot_pages[s]
            if self._slot_req[s] is None:
                if mapped or row.any():
                    raise AssertionError(
                        f"page invariant: free slot {s} still maps pages "
                        f"{mapped} (table row {row.tolist()})"
                    )
                continue
            if len(set(mapped)) != len(mapped) or 0 in mapped:
                raise AssertionError(
                    f"page invariant: slot {s} mapping {mapped} has "
                    "duplicates or scratch"
                )
            nz = [int(p) for p in row if p]
            if nz != list(mapped):
                raise AssertionError(
                    f"page invariant: slot {s} table row {row.tolist()} "
                    f"disagrees with its mapping {mapped}"
                )
            frontier = (int(self._pos[s]) - 1) // ps
            if frontier >= len(mapped):
                raise AssertionError(
                    f"page invariant: slot {s} at pos {int(self._pos[s])} "
                    f"writes page entry {frontier} but maps only "
                    f"{len(mapped)} pages"
                )
            if self._spec_on:
                # the next verify window may legally overrun the mapping
                # (rejected-draft territory), but every overrun entry must
                # scatter to scratch — a nonzero table value there would
                # aim garbage at a live page
                _win_in, win_over = spec_write_pages(
                    int(self._pos[s]), self.spec_k + 1, ps, len(mapped)
                )
                for e in win_over:
                    if e < row.shape[0] and row[e] != 0:
                        raise AssertionError(
                            f"page invariant: slot {s} verify window entry "
                            f"{e} is past its {len(mapped)}-page mapping but "
                            f"table row holds page {int(row[e])} (expected "
                            "0 = scratch redirect)"
                        )
            for p in mapped:
                expected[p] += 1
        if self._prefix is not None:
            for e in self._prefix.entries():
                if not 0 < e.rows <= ps:
                    raise AssertionError(
                        f"page invariant: cache entry on page {e.page} has "
                        f"row count {e.rows} outside (0, {ps}]"
                    )
                expected[e.page] += 1
        if not np.array_equal(expected, pool.refs):
            bad = [
                (p, int(pool.refs[p]), int(expected[p]))
                for p in range(pool.num_pages)
                if pool.refs[p] != expected[p]
            ]
            raise AssertionError(
                "page invariant: refcount drift (page, actual, expected): "
                f"{bad}"
            )
        free = sorted(pool._free)
        ref0 = [
            p for p in range(pool.num_pages)
            if pool.refs[p] == 0 and not pool.is_scratch(p)
        ]
        if free != ref0 or len(set(free)) != len(free):
            raise AssertionError(
                f"page invariant: free list {free} != ref-0 pages {ref0}"
            )
        if self.cp > 1:
            # cp layout invariant: every mapped sequence page sits on the
            # shard its table column demands (column j -> shard j % cp) —
            # a misplaced page would silently read as unmapped on device
            for s in range(self.slots):
                if self._slot_req[s] is None:
                    continue
                row = self._page_table[s]
                for j in range(row.shape[0]):
                    p = int(row[j])
                    if p and pool.shard_of(p) != j % self.cp:
                        raise AssertionError(
                            f"page invariant: slot {s} table column {j} "
                            f"maps page {p} on shard {pool.shard_of(p)}, "
                            f"expected shard {j % self.cp} (cp={self.cp})"
                        )
        if self._sessions is not None:
            # ISSUE 20 audit clause: session pins reconcile exactly with
            # live cache entries and their page refcounts
            self._sessions.check(self._prefix, pool)
