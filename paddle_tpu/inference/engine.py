"""Continuous-batching inference engine (reference capability: the inference
runtime's flash-decode serving path, SURVEY §2.1 L8 — scheduling layer).

The lock-step `GenerationPredictor` runs every request in a batch from first
token to last together: one long generation holds the whole batch hostage,
and a new request waits for the batch to drain.  This engine instead owns a
persistent SLOT POOL of `StaticKVCache` buffers (`[slots, max_len, kv_heads,
head_dim]` per layer) and runs ONE compiled decode step whatever the
occupancy: per-slot `pos` and `active` masks are DATA, never shapes, so
requests joining, finishing, and slots being recycled cause zero recompiles
after warmup.

New requests are prefilled through length-bucketed compiled prefill
executables — the prompt pads up to its bucket, attends to itself causally,
and its K/V land in the assigned pool slot (slot index is data too, so one
executable per bucket serves every slot).  Prefills interleave with in-flight
decode at step granularity; finished slots (EOS or max_new_tokens) are
recycled immediately.

Why padding garbage is safe: a prefill writes rows [0, bucket) of its slot,
rows [true_len, bucket) holding padding K/V.  Decode at position p first
overwrites row p, then attends rows j <= p only — every garbage row is
overwritten by the decode step that first brings it into the attended window.
Inactive slots decode with pos forced to 0; their row-0 write is scratch
because the next prefill into that slot always rewrites row 0.

Compiled-executable budget: len(prefill_buckets) + 1 (asserted by tests via
`compile_counts()`).  Both functions ride @to_static, so PR 3's persistent
compile cache and AOT snapshots apply per bucket: a restarted server binds
the previous process's executables without tracing.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from ..framework import core as _fcore
from ..models.llama import SlotView, StaticKVCache
from ..tensor import Tensor


class QueueFull(RuntimeError):
    """Admission queue at capacity — submit() fails fast (serve() maps this
    to HTTP 503)."""


class EngineRequest:
    """Handle for one submitted generation: streaming callback target,
    completion event, and timing for the serving gauges."""

    def __init__(self, prompt, max_new_tokens, temperature, eos_token_id, on_token):
        self.prompt = prompt  # np.int32 [L]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_token_id = eos_token_id
        self.on_token = on_token
        self.tokens = []  # generated ids (includes eos when hit)
        self.finished = threading.Event()
        self.finish_reason = None  # "eos" | "length" | "error"
        self.error = None
        self.ttft_s = None
        self._submit_t = None
        self._finish_t = None

    def wait(self, timeout=None):
        """Block until the request finishes; returns prompt + generated ids."""
        if not self.finished.wait(timeout):
            raise TimeoutError(
                f"generation not finished after {timeout}s "
                f"({len(self.tokens)}/{self.max_new_tokens} tokens)"
            )
        if self.error is not None:
            raise self.error
        return np.concatenate([self.prompt, np.asarray(self.tokens, np.int32)])


class ContinuousBatchingEngine:
    """Slot-pooled continuous-batching engine over a causal-LM with the
    compiled static-KV decode contract (`model.llama(toks, caches=, pos=)` +
    `model.lm_head`, i.e. LlamaForCausalLM and shape-compatible models).

    submit() enqueues (bounded admission queue -> QueueFull); the scheduler —
    either the background thread started by start()/serve(), or synchronous
    step()/run_until_idle() calls — admits queued requests into free slots
    via bucketed prefill and advances all active slots one token per decode
    step.  Tokens stream through per-request `on_token` callbacks as they are
    produced.
    """

    def __init__(self, model, slots=None, max_len=None, prefill_buckets=None,
                 queue_depth=None, seed=0):
        import jax

        from .. import jit, to_tensor

        cfg = model.config
        self.model = model
        self.slots = int(slots if slots is not None else _fcore.flag("FLAGS_serve_slots"))
        max_len = max_len if max_len is not None else cfg.max_position_embeddings
        # rope tables (and therefore positions) top out at max_position_embeddings
        self.max_len = int(min(max_len, cfg.max_position_embeddings))
        if prefill_buckets is None:
            raw = str(_fcore.flag("FLAGS_serve_prefill_buckets"))
            prefill_buckets = [int(x) for x in raw.split(",") if x.strip()]
        self.prefill_buckets = sorted(
            {int(b) for b in prefill_buckets if 0 < int(b) < self.max_len}
        )
        if not self.prefill_buckets:
            raise ValueError("prefill_buckets must contain a value < max_len")
        self.queue_depth = int(
            queue_depth if queue_depth is not None else _fcore.flag("FLAGS_serve_queue_depth")
        )

        # generation is inference: dropout must not bake into the cached
        # executables (they outlive any later train() switch)
        if getattr(model, "training", False):
            model.eval()

        head_dim = cfg.hidden_size // cfg.num_attention_heads
        cache_dtype = model.lm_head.weight.dtype  # bf16 under AMP-O2 decorate
        self._caches = [
            StaticKVCache(self.slots, self.max_len, cfg.num_key_value_heads,
                          head_dim, cache_dtype)
            for _ in range(cfg.num_hidden_layers)
        ]
        self._decode_fn = jit.to_static(self._decode_body)
        self._prefill_fn = jit.to_static(self._prefill_body)
        self._key = to_tensor(np.asarray(jax.random.PRNGKey(int(seed))))

        # host-side slot table — touched only by the scheduling thread
        self._slot_req = [None] * self.slots
        self._pos = np.zeros(self.slots, np.int32)
        self._last_tok = np.zeros(self.slots, np.int32)
        self._temps = np.zeros(self.slots, np.float32)
        # device-resident decode loop state (toks, pos, active, temps),
        # rebuilt from the host mirrors only when slot membership changes
        self._dev = None
        # decode steps dispatched but not yet fetched to host: [(nxt, idx)]
        self._pending_fetch = []

        self._queue = queue.Queue(maxsize=self.queue_depth)
        self._cv = threading.Condition()
        self._thread = None
        self._stop = False

    # -- compiled bodies ----------------------------------------------------

    def _decode_body(self, toks, pos, active, temps, key):
        """One token for every slot: toks [S,1], pos [S], active [S] bool,
        temps [S] f32 (0 = greedy, >0 = sampled — per-slot, as data), key
        uint32[2].  Inactive slots run at pos 0 (scratch, see module doc).
        Returns (next tokens [S,1], advanced pos [S], key): the loop state is
        device-resident and threads straight back in — between membership
        changes a decode step costs one executable dispatch plus the [S]
        token fetch, zero host->device transfers."""
        import jax
        import jax.numpy as jnp

        from ..ops.dispatch import apply

        pos_eff = apply(
            lambda p, a: jnp.where(a, p, 0), [pos, active], name="serve_pos_mask"
        )
        hidden, _ = self.model.llama(toks, caches=self._caches, pos=pos_eff)
        logits = self.model.lm_head(hidden)[:, -1]  # [S, V]

        def f(lg, ky, tp, p, a):
            lgf = lg.astype(jnp.float32)
            greedy = jnp.argmax(lgf, axis=-1).astype(jnp.int32)
            ky, sub = jax.random.split(ky)
            samp = jax.random.categorical(
                sub, lgf / jnp.maximum(tp, 1e-6)[:, None], axis=-1
            ).astype(jnp.int32)
            nxt = jnp.where(tp > 0.0, samp, greedy)
            return nxt[:, None], jnp.where(a, p + 1, p), ky

        nxt, new_pos, key = apply(
            f, [logits, key, temps, pos, active], multi=True, name="serve_sample"
        )
        return nxt, new_pos, key

    def _prefill_body(self, toks, slot, true_len, temp, key):
        """Bucketed prefill: toks [1, bucket] (right-padded), slot / true_len
        scalars (data).  Writes K/V into pool rows [0, bucket) of `slot` and
        returns the first generated token from the logits at true_len - 1."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops.dispatch import apply

        views = [SlotView(c, slot) for c in self._caches]
        hidden, _ = self.model.llama(toks, caches=views)
        h_last = apply(
            lambda h, n: lax.dynamic_slice_in_dim(h, n - 1, 1, 1),
            [hidden, true_len], name="serve_prefill_last",
        )
        logits = self.model.lm_head(h_last)[:, -1]  # [1, V]

        def f(lg, ky, tp):
            lgf = lg.astype(jnp.float32)
            greedy = jnp.argmax(lgf, axis=-1).astype(jnp.int32)
            ky, sub = jax.random.split(ky)
            samp = jax.random.categorical(
                sub, lgf / jnp.maximum(tp, 1e-6), axis=-1
            ).astype(jnp.int32)
            return jnp.where(tp > 0.0, samp, greedy), ky

        nxt, key = apply(f, [logits, key, temp], multi=True, name="serve_sample1")
        return nxt, key

    # -- public API ---------------------------------------------------------

    def submit(self, input_ids, max_new_tokens=32, temperature=0.0,
               eos_token_id=None, on_token=None):
        """Enqueue one request (1-D token ids).  Returns an EngineRequest
        handle immediately; raises QueueFull when the admission queue is at
        capacity."""
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        if ids.size >= self.max_len:
            raise ValueError(
                f"prompt length {ids.size} >= engine max_len {self.max_len}"
            )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        req = EngineRequest(ids, max_new_tokens, temperature, eos_token_id, on_token)
        req._submit_t = time.perf_counter()
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            raise QueueFull(
                f"admission queue full ({self.queue_depth} pending)"
            ) from None
        with self._cv:
            self._cv.notify()
        return req

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 eos_token_id=None, timeout=None):
        """Submit + wait.  Drives the scheduler inline when no background
        thread is running; returns prompt + generated ids (np.int32)."""
        req = self.submit(input_ids, max_new_tokens=max_new_tokens,
                          temperature=temperature, eos_token_id=eos_token_id)
        if self._thread is None:
            self.run_until_idle()
        return req.wait(timeout)

    def warmup(self):
        """Trace/compile (or AOT-load via FLAGS_compile_cache_dir) every
        prefill bucket and the decode step before traffic arrives.  Dummy
        data through the real executables; the rows it scribbles into slot 0
        are rewritten by that slot's next real prefill.  Call before start().
        """
        from .. import to_tensor

        for b in self.prefill_buckets:
            _, self._key = self._prefill_fn(
                to_tensor(np.zeros((1, b), np.int32)),
                to_tensor(np.int32(0)), to_tensor(np.int32(b)),
                to_tensor(np.float32(0.0)), self._key,
            )
        _, _, self._key = self._decode_fn(
            to_tensor(np.zeros((self.slots, 1), np.int32)),
            to_tensor(np.zeros(self.slots, np.int32)),
            to_tensor(np.zeros(self.slots, bool)),
            to_tensor(np.zeros(self.slots, np.float32)),
            self._key,
        )
        return self

    def compile_counts(self):
        """{prefill, decode} trace counts + AOT snapshot hits — the test
        contract is prefill == len(buckets used) and decode == 1, forever."""
        return {
            "prefill": self._prefill_fn.trace_count,
            "decode": self._decode_fn.trace_count,
            "aot_hits": self._prefill_fn.aot_hits + self._decode_fn.aot_hits,
        }

    @property
    def active_slots(self):
        return sum(1 for r in self._slot_req if r is not None)

    @property
    def pending(self):
        return self._queue.qsize()

    # -- scheduler ----------------------------------------------------------

    def step(self):
        """One scheduling tick: admit queued requests into free slots
        (bucketed prefill), then advance every active slot one token.
        Returns the number of tokens emitted (prefill first-tokens included).
        Synchronous alternative to start() — never mix the two."""
        emitted = self._admit()
        return emitted + self._decode_once()

    def run_until_idle(self):
        """Drive step() until queue and slots are empty (synchronous mode)."""
        total = 0
        while self._queue.qsize() or self.active_slots:
            total += self.step()
        return total

    def start(self):
        """Run the scheduler on a daemon thread (serve() calls this)."""
        if self._thread is not None:
            return self
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="cb-engine", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        if self._thread is None:
            return
        self._stop = True
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout=30)
        self._thread = None

    def _loop(self):
        while not self._stop:
            if not self._queue.qsize() and not self.active_slots:
                with self._cv:
                    if not self._stop and not self._queue.qsize():
                        self._cv.wait(timeout=0.05)
                continue
            try:
                self.step()
            except Exception as e:  # poison every in-flight request, keep serving
                self._pending_fetch.clear()
                for s, req in enumerate(self._slot_req):
                    if req is not None:
                        req.error = e
                        self._finish(s, req, "error")

    # -- internals ----------------------------------------------------------

    def _bucket_for(self, n):
        for b in self.prefill_buckets:
            if n <= b:
                return b
        # over-bucket prompt: grow a next-power-of-two bucket (one extra
        # compile, then cached/snapshotted like any other)
        b = min(1 << (n - 1).bit_length(), self.max_len - 1)
        self.prefill_buckets.append(b)
        self.prefill_buckets.sort()
        return b

    def _admit(self):
        emitted = 0
        for s in range(self.slots):
            if self._slot_req[s] is not None:
                continue
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            try:
                self._prefill_into(s, req)
                emitted += 1
            except Exception as e:  # fail THIS request, keep the engine alive
                req.error = e
                if self._slot_req[s] is req:
                    self._finish(s, req, "error")
                else:
                    req.finish_reason = "error"
                    req.finished.set()
        return emitted

    def _prefill_into(self, s, req):
        from .. import to_tensor

        # the rebuild after this membership change reads _last_tok — it must
        # reflect every step already dispatched
        self._flush_pending()
        L = int(req.prompt.size)
        bucket = self._bucket_for(L)
        # cache rows run out at max_len: the last writable decode row is
        # max_len - 1, giving max_len - L generatable tokens
        req.max_new_tokens = min(req.max_new_tokens, self.max_len - L)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :L] = req.prompt
        nxt, self._key = self._prefill_fn(
            to_tensor(toks), to_tensor(np.int32(s)), to_tensor(np.int32(L)),
            to_tensor(np.float32(req.temperature)), self._key,
        )
        tok = int(np.asarray(nxt.numpy()).reshape(-1)[0])
        req.ttft_s = time.perf_counter() - req._submit_t
        self._slot_req[s] = req
        self._pos[s] = L
        self._last_tok[s] = tok
        self._temps[s] = req.temperature
        self._dev = None  # membership changed: rebuild device loop state
        self._emit(s, req, tok)

    def _decode_once(self):
        from .. import profiler as _prof
        from .. import to_tensor

        active_idx = [s for s in range(self.slots) if self._slot_req[s] is not None]
        if not active_idx:
            return 0
        t0 = time.perf_counter()
        if self._dev is None:
            active = np.zeros(self.slots, bool)
            active[active_idx] = True
            self._dev = (
                to_tensor(self._last_tok.reshape(self.slots, 1)),
                to_tensor(self._pos.copy()), to_tensor(active),
                to_tensor(self._temps.copy()),
            )
        toks_t, pos_t, active_t, temps_t = self._dev
        nxt, new_pos, self._key = self._decode_fn(
            toks_t, pos_t, active_t, temps_t, self._key
        )
        self._dev = (nxt, new_pos, active_t, temps_t)
        for s in active_idx:
            self._pos[s] += 1
        # fetch to host only when something needs the values this step — a
        # per-token consumer (EOS watch, streaming callback) or a slot hitting
        # its length bound.  Otherwise the step stays in flight and the sync
        # lands at the next membership change, so XLA pipelines decode
        # dispatches exactly like the lock-step generate loop.
        self._pending_fetch.append((nxt, active_idx))
        depth = len(self._pending_fetch)
        if any(
            self._slot_req[s].eos_token_id is not None
            or self._slot_req[s].on_token is not None
            or len(self._slot_req[s].tokens) + depth
            >= self._slot_req[s].max_new_tokens
            for s in active_idx
        ):
            self._flush_pending()
        _prof.record_serving_tick(
            len(active_idx) / self.slots, self._queue.qsize(),
            time.perf_counter() - t0,
        )
        return len(active_idx)

    def _flush_pending(self):
        """Fetch every dispatched-but-unfetched decode step and emit its
        tokens.  Membership is constant across buffered steps (any change
        flushes first), so each entry's active set is exact."""
        if not self._pending_fetch:
            return
        batches, self._pending_fetch = self._pending_fetch, []
        for nxt, idx in batches:
            nxt_np = np.asarray(nxt.numpy()).reshape(-1)
            for s in idx:
                req = self._slot_req[s]
                if req is None:  # finished earlier in this flush
                    continue
                tok = int(nxt_np[s])
                self._last_tok[s] = tok
                self._emit(s, req, tok)

    def _emit(self, s, req, tok):
        req.tokens.append(tok)
        if req.on_token is not None:
            try:
                req.on_token(tok)
            except Exception:
                pass  # a broken consumer must not take the engine down
        if req.eos_token_id is not None and tok == req.eos_token_id:
            self._finish(s, req, "eos")
        elif len(req.tokens) >= req.max_new_tokens:
            self._finish(s, req, "length")

    def _finish(self, s, req, reason):
        from .. import profiler as _prof

        req.finish_reason = reason
        req._finish_t = time.perf_counter()
        # recycle immediately: no cache scrub needed — the slot's next
        # prefill overwrites rows [0, bucket) and decode masks the rest
        self._slot_req[s] = None
        self._pos[s] = 0
        self._last_tok[s] = 0
        self._temps[s] = 0.0
        self._dev = None  # membership changed: rebuild device loop state
        if reason != "error":
            _prof.record_serving_request(
                req.ttft_s or 0.0, len(req.tokens),
                req._finish_t - req._submit_t,
            )
        req.finished.set()
