"""Inference export/serving (reference capability:
paddle/fluid/inference AnalysisPredictor + paddle.jit.save inference models —
SURVEY.md §2.1 "Inference runtime").

TPU-native path: the exported artifact is a serialized StableHLO program
(jax.export) + weights — portable across machines with compatible jaxlib,
re-compiled by XLA on load (the reference ships ProgramDesc + params and
re-optimizes with IR passes; same shape).
"""

from __future__ import annotations

import os
import pickle

import numpy as np
import jax

from .. import no_grad
from ..framework.io import load as _load
from ..framework.io import save as _save
from ..tensor import Tensor


def export(layer, path, example_inputs, with_weights=True, params_from=None):
    """Serialize `layer.forward` (or a plain callable) traced at
    example_inputs to StableHLO.

    example_inputs: list of Tensors/arrays defining shapes+dtypes.
    params_from: Layer whose state_dict to save when `layer` is a bare
    callable (e.g. a @to_static-decorated bound method).
    Produces: <path>.stablehlo (serialized program), <path>.pdiparams.
    """
    from jax import export as jexport

    weights_owner = params_from if params_from is not None else layer
    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    arrays = [
        (x._raw if isinstance(x, Tensor) else np.asarray(x)) for x in example_inputs
    ]

    def pure_fn(*xs):
        ts = []
        for a in xs:
            t = Tensor.__new__(Tensor)
            t._init_from_array(a, stop_gradient=True)
            ts.append(t)
        with no_grad():
            out = layer(*ts)
        if isinstance(out, Tensor):
            return out._raw
        return tuple(o._raw if isinstance(o, Tensor) else o for o in out)

    exported = jexport.export(jax.jit(pure_fn))(
        *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrays]
    )
    blob = exported.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".stablehlo", "wb") as f:
        f.write(blob)
    if with_weights and hasattr(weights_owner, "state_dict"):
        _save(weights_owner.state_dict(), path + ".pdiparams")
    if was_training:
        layer.train()  # export must not flip the live model to eval
    return path


class Config:
    """API-compat config object (reference: paddle_infer::Config)."""

    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path

    def enable_use_gpu(self, *a, **k):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def switch_ir_optim(self, flag=True):
        pass


class Predictor:
    """Loads a serialized StableHLO program and runs it (reference:
    AnalysisPredictor::Run)."""

    def __init__(self, path_or_config):
        path = (
            path_or_config.model_path
            if isinstance(path_or_config, Config)
            else path_or_config
        )
        from jax import export as jexport

        with open(path + ".stablehlo", "rb") as f:
            self._exported = jexport.deserialize(f.read())
        # jit the exported call so its compile goes through jax's compilation
        # cache — with FLAGS_compile_cache_dir set, a restarted server loads
        # the XLA binary from disk instead of recompiling the program
        self._call = jax.jit(self._exported.call)

    def run(self, inputs):
        arrays = [
            x._raw if isinstance(x, Tensor) else np.asarray(x) for x in inputs
        ]
        out = self._call(*arrays)
        if isinstance(out, (list, tuple)):
            return [np.asarray(o) for o in out]
        return [np.asarray(out)]

    def get_input_names(self):
        return [f"x{i}" for i in range(len(self._exported.in_avals))]

    def get_output_names(self):
        return [f"y{i}" for i in range(len(self._exported.out_avals))]


def create_predictor(config):
    return Predictor(config)


class GenerationPredictor:
    """Serves autoregressive decoding over a model's compiled static-KV
    decode step (models/llama.py StaticKVCache): the first request compiles
    prefill+decode once; every later token — and every later request with
    the same batch/cache bucket — reuses the same two executables.
    (Reference capability: the inference runtime's flash-decode serving
    path, SURVEY §2.1 L8.)"""

    def __init__(self, model, max_new_tokens=32):
        self.model = model
        self.max_new_tokens = max_new_tokens

    def generate(self, input_ids, max_new_tokens=None, temperature=0.0,
                 eos_token_id=None):
        ids = np.asarray(input_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        n = self.max_new_tokens if max_new_tokens is None else int(max_new_tokens)
        out = self.model.generate(
            Tensor(ids), max_new_tokens=n, temperature=float(temperature),
            eos_token_id=eos_token_id,
        )
        return np.asarray(out.numpy())

    def warmup(self, batch_size=1, prompt_len=8, max_new_tokens=None, temperature=0.0):
        """Compile (or AOT-load, with FLAGS_compile_cache_dir set) the
        prefill + decode executables for one serving bucket before traffic
        arrives, so the first request pays no cold-start compile.  Runs a
        dummy generate on zero ids — model weights are read-only in decode,
        nothing is mutated."""
        ids = np.zeros((int(batch_size), int(prompt_len)), np.int32)
        self.generate(ids, max_new_tokens=max_new_tokens, temperature=temperature)
        return self


def serve(path_or_predictor, port=8866, host="127.0.0.1", block=True,
          supervise=True, handle_signals=None):
    """Serving loop (reference capability: the AnalysisPredictor behind
    paddle_serving — SURVEY.md §2.1 "Inference runtime").  Stdlib-only
    ThreadingHTTPServer with a bounded admission gate: requests beyond the
    queue bound (FLAGS_serve_queue_depth) get 503 + JSON instead of piling
    up behind the executable.

    - GET  /health            -> 200
    - GET  /healthz           -> lifecycle snapshot: status live/ready/
      draining/dead + occupancy, queue depth, restart count, drain estimate
    - GET  /metrics           -> Prometheus text exposition (profiler,
      sanitizer, trace and flight-recorder counters; replica label)
    - GET  /trace/<id>        -> per-request span tree (populated when
      FLAGS_trace is on; POST responses carry X-Trace-Id)
    - POST /predict           -> {"outputs": [...]}   (Predictor)
    - POST /generate          -> {"tokens": [...]}    (GenerationPredictor or
      ContinuousBatchingEngine; body: {"input_ids": [...] or [[...], ...],
      "max_new_tokens": n, "temperature": t, "eos_token_id": id,
      "deadline_s": s, "spec_k": k, "adapter": name,
      "session_id": sid}).  "spec_k" caps the
      request's speculative draft length below the engine-wide
      FLAGS_serve_spec_k (0 opts out of speculation; omitted = engine
      default).  "adapter" names a registered LoRA adapter served from the
      engine's adapter arena (omitted = base model); an unregistered name
      is a typed 404 (`AdapterUnknown`, retriable: false).  An
      `X-Idempotency-Key` header dedupes server-side: a completed key
      replays its cached response byte-identical (marked
      `X-Idempotency-Replay`) within `FLAGS_router_idem_ttl`, an in-flight
      key joins the live generation — at most one generation per key even
      through connection resets and router failover.  "session_id" (ISSUE
      20) names a multi-turn KV session on this replica: the engine pins
      the conversation's committed pages and later turns chunk-prefill
      only the new suffix.  A prompt past the engine's context is a typed
      400 (`ContextOverflow`, retriable: false) whose body carries the
      capacity geometry (`max_len`, and under cp the per-shard page
      budget) — raised at admission, before any page is touched
    - POST /prefill           -> disaggregated prefill hop (engine-backed,
      ISSUE 19): runs chunked prefill + ONE sampled token, exports the
      committed prompt pages, and answers {"first_token", "prompt_len",
      "handoff"} — the handoff payload a decode-role replica imports via
      /generate's "handoff" field (paired with a "reservation" from
      /reserve).  Quantized arenas ship int8 rows + scales as stored.
      With "export": false (the router's single-token fast path) the page
      export is skipped and "handoff" is null — the sampled token is the
      entire response.
    - POST /reserve           -> {"prompt_len": L, "max_new_tokens": n}
      reserves decode-side pages BEFORE prefill starts elsewhere; answers
      {"reservation", "pages", "ttl_s"} or typed 503 when the headroom
      isn't there.  Unconsumed reservations expire after ttl_s.

    A ContinuousBatchingEngine serves /generate with true continuous
    batching: concurrent requests decode interleaved in the slot pool, each
    finishing on its own EOS/length (the lock-based predictors serialize).

    Serving fault domain (PR 6): an engine-backed server runs under a
    ``fault.EngineSupervisor`` (``supervise=False`` opts out) — a wedged or
    dead scheduler gets a bounded warm restart, and past the budget clients
    get typed 503s instead of hangs.  Every 503 carries a ``Retry-After``
    header derived from the engine's queue-drain estimate.  SIGTERM (when
    serve() runs on the main thread, or ``handle_signals=True``) triggers
    DRAIN: stop admitting, finish in-flight work up to ``PADDLE_STOP_GRACE``
    seconds (exported by ``distributed.launch --stop_grace``; else
    ``FLAGS_serve_drain_grace``), then stop cleanly.  ``server.drain(grace)``
    does the same programmatically.
    """
    import json
    import signal as _signal
    import threading
    import time as _time
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from . import engine as engine_mod
    from .engine import ContinuousBatchingEngine, EngineUnavailable
    from ..lora.registry import AdapterUnknown
    from ..fault import EngineSupervisor
    from ..framework import core as _fcore
    from ..obs import flight as _flight
    from ..obs import metrics as _obs_metrics
    from ..obs import trace as _obs

    predictor = (
        path_or_predictor
        if isinstance(
            path_or_predictor,
            (Predictor, GenerationPredictor, ContinuousBatchingEngine),
        )
        else Predictor(path_or_predictor)
    )
    engine = predictor if isinstance(predictor, ContinuousBatchingEngine) else None
    supervisor = None
    if engine is not None:
        engine.start()
        if supervise:
            supervisor = EngineSupervisor(engine).start()
    lock = threading.Lock()
    # admission bound for the lock-based predictor paths: at most
    # queue_depth requests running-or-waiting; the rest shed with 503
    # (the engine has its own bounded queue — submit raises QueueFull)
    gate = threading.BoundedSemaphore(int(_fcore.flag("FLAGS_serve_queue_depth")))
    state = {"draining": False}
    # crash-proof front door (ISSUE 17): replica-side request dedupe.  A
    # /generate carrying X-Idempotency-Key completes into this cache BEFORE
    # its response bytes go out, so a connection reset (or a dead router)
    # after the generation finished leaves the response replayable — the
    # retry through the successor router gets the SAME bytes, not a second
    # generation.  journal-module import is stdlib-light by design.
    idem = None
    if engine is not None:
        from ..serving.journal import IdempotencyCache

        idem = IdempotencyCache()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _reply(self, code, payload, headers=None):
            key = getattr(self, "_idem_key", None)
            if key is not None and idem is not None:
                # complete BEFORE any response byte leaves: a reset between
                # completion and delivery must leave the response cached for
                # the client's (or successor router's) keyed retry
                self._idem_key = None
                idem.complete(key, code, payload, headers)
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            tid = getattr(self, "_trace_id", None)
            if tid:
                self.send_header(_obs.HDR_TRACE, tid)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_error(self, code, err_type, msg, retriable, retry_after=None):
            # uniformly typed error JSON: the router's retry decision is
            # driven by `retriable` + Retry-After, never by string matching;
            # trace_id joins the failure to its span tree across hops
            self._err = err_type
            headers = {}
            # `is not None`, not truthiness (the router-side fix's twin):
            # a 0.0 drain estimate still means "retry after 1s", not
            # "no header"
            if retry_after is not None:
                headers["Retry-After"] = str(max(1, int(retry_after + 0.5)))
            self._reply(
                code,
                {
                    "error": msg,
                    "type": err_type,
                    "retriable": bool(retriable),
                    "retry_after_s": retry_after or 0,
                    "trace_id": getattr(self, "_trace_id", None),
                },
                headers,
            )

        def _busy(self, msg="admission queue full, retry later",
                  retry_after=None, err_type="EngineUnavailable"):
            # Retry-After from the queue-drain estimate: a shed client
            # retries when a slot is plausibly free, not immediately
            if retry_after is None and engine is not None:
                retry_after = engine.estimate_drain_s()
            self._reply_error(503, err_type, msg, True, retry_after)

        def _healthz(self):
            if engine is not None:
                h = engine.healthz()
                if state["draining"] and h["status"] not in ("dead",):
                    h["status"] = "draining"
            else:
                h = {"status": "draining" if state["draining"] else "ready"}
            code = 200 if h["status"] in ("ready", "live") else 503
            self._reply(code, h)

        def do_GET(self):
            if self.path == "/health":
                self._reply(200, {"status": "ok"})
            elif self.path == "/healthz":
                self._healthz()
            elif self.path == "/metrics":
                # bound address, not the port argument (0 = ephemeral)
                bh, bp = self.server.server_address[:2]
                body = _obs_metrics.render(
                    labels={"replica": f"{bh}:{bp}"}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", _obs_metrics.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith("/trace/"):
                tid = self.path[len("/trace/"):]
                roots = _obs.tree(tid)
                if roots:
                    self._reply(200, {"trace_id": tid, "spans": roots})
                else:
                    self._reply(404, {"error": f"no spans buffered for trace {tid!r}"})
            else:
                self._reply(404, {"error": "use POST /predict"})

        def _deadline_s(self, req):
            # per-request deadline: body field, else the router's
            # X-Deadline-Ms hop header (remaining budget at send time)
            d = req.get("deadline_s")
            if d is not None:
                return float(d)
            hdr = self.headers.get("X-Deadline-Ms")
            if hdr is not None:
                return float(hdr) / 1e3
            return None

        def _generate_engine(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                ids = req["input_ids"]
                deadline_s = self._deadline_s(req)
                if deadline_s is not None and deadline_s <= 0:
                    # the hop budget was spent in flight; don't even admit
                    self._reply_error(
                        504, "DeadlineExceeded",
                        "deadline exhausted before admission", False,
                    )
                    return
                rows = ids if ids and isinstance(ids[0], list) else [ids]
                handles = []
                try:
                    for row in rows:
                        handles.append(
                            engine.submit(
                                row,
                                max_new_tokens=int(req.get("max_new_tokens") or 32),
                                temperature=float(req.get("temperature", 0.0)),
                                eos_token_id=req.get("eos_token_id"),
                                deadline_s=deadline_s,
                                trace=(self._trace_id, self._handle_sid),
                                spec_k=(
                                    None if req.get("spec_k") is None
                                    else int(req["spec_k"])
                                ),
                                adapter=req.get("adapter"),
                                handoff=req.get("handoff"),
                                reservation=req.get("reservation"),
                                session_id=req.get("session_id"),
                            )
                        )
                except engine_mod.ContextOverflow as e:
                    # typed 400, terminal: no replica of this tier holds
                    # more context — the body carries the capacity geometry
                    # so the client can right-size or re-route by itself
                    self._err = type(e).__name__
                    self._reply(400, {
                        "error": str(e),
                        "type": type(e).__name__,
                        "retriable": False,
                        "retry_after_s": 0,
                        "capacity": e.body(),
                        "trace_id": getattr(self, "_trace_id", None),
                    })
                    return
                except AdapterUnknown as e:
                    # terminal 404: retrying cannot help until someone
                    # registers the adapter — the router must NOT fail over
                    self._reply_error(404, type(e).__name__, str(e), False)
                    return
                except engine_mod.DeadlineUnattainable as e:
                    # 504 but retriable: a LESS LOADED replica may still
                    # meet the deadline — the router fails over on this
                    self._reply_error(
                        504, type(e).__name__, str(e), True, e.retry_after_s
                    )
                    return
                except EngineUnavailable as e:
                    # queue full / draining / dead: rows already admitted
                    # still complete server-side; the client sheds and
                    # retries the whole batch
                    self._busy(str(e), retry_after=e.retry_after_s,
                               err_type=type(e).__name__)
                    return
                outs = [h.wait(timeout=600).tolist() for h in handles]
                self._reply(
                    200,
                    {"tokens": outs if isinstance(ids[0], list) else outs[0]},
                )
            except engine_mod.EngineRestarted as e:
                # in-flight state was lost to a warm restart: typed 503,
                # the request is safe to retry (no tokens were delivered)
                self._busy(str(e), err_type=type(e).__name__)
            except engine_mod.DeadlineExceeded as e:
                # the deadline passed while queued/decoding: retrying the
                # same budget elsewhere cannot succeed
                self._reply_error(504, type(e).__name__, str(e), False)
            except engine_mod.NonFiniteLogits as e:
                self._reply_error(500, type(e).__name__, str(e), False)
            except Exception as e:
                self._reply_error(
                    400, type(e).__name__, f"{type(e).__name__}: {e}", False
                )

        def _reserve_engine(self):
            # decode-side page hold, taken BEFORE prefill starts elsewhere:
            # the router reserves here, prefills on the prefill worker, then
            # spends the reservation in /generate's admission — so a prefill
            # never completes into a decode worker that can't seat it
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                out = engine.reserve_pages(
                    int(req["prompt_len"]),
                    int(req.get("max_new_tokens") or 32),
                    ttl_s=(
                        None if req.get("ttl_s") is None
                        else float(req["ttl_s"])
                    ),
                )
                self._reply(200, out)
            except EngineUnavailable as e:
                self._busy(str(e), retry_after=e.retry_after_s,
                           err_type=type(e).__name__)
            except Exception as e:
                self._reply_error(
                    400, type(e).__name__, f"{type(e).__name__}: {e}", False
                )

        def _prefill_engine(self):
            from ..fault import injection as _inj

            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                ids = req["input_ids"]
                if ids and isinstance(ids[0], list):
                    self._reply_error(
                        400, "ValueError",
                        "/prefill takes one prompt per request", False,
                    )
                    return
                deadline_s = self._deadline_s(req)
                if deadline_s is not None and deadline_s <= 0:
                    self._reply_error(
                        504, "DeadlineExceeded",
                        "deadline exhausted before admission", False,
                    )
                    return
                # export=false is the router's single-token fast path: the
                # sampled token is the entire response, so no page export
                # (and no handoff) is ever built
                want_export = bool(req.get("export", True))
                try:
                    # one sampled token: the decode side seats pos=L with
                    # this token as its first emission, so the handoff is
                    # exactly a colocated engine's post-prefill state.
                    # spec_k=0 — a 1-token request has nothing to draft.
                    h = engine.submit(
                        ids,
                        max_new_tokens=1,
                        temperature=float(req.get("temperature", 0.0)),
                        eos_token_id=req.get("eos_token_id"),
                        deadline_s=deadline_s,
                        trace=(self._trace_id, self._handle_sid),
                        spec_k=0,
                        export_kv=want_export,
                    )
                except engine_mod.DeadlineUnattainable as e:
                    self._reply_error(
                        504, type(e).__name__, str(e), True, e.retry_after_s
                    )
                    return
                except EngineUnavailable as e:
                    self._busy(str(e), retry_after=e.retry_after_s,
                               err_type=type(e).__name__)
                    return
                out = h.wait(timeout=600)
                if _inj.should_fire("disagg.prefill.crash", "serve./prefill"):
                    # kill -9 mid-handoff: the payload exists server-side
                    # but not one response byte leaves, so the router sees
                    # a transport error with response_started=False — a
                    # zero-token retriable failover, never a duplicate
                    self.close_connection = True
                    return
                if not want_export:
                    self._reply(200, {
                        "first_token": int(out[len(ids)]),
                        "prompt_len": len(ids),
                        "handoff": None,
                    })
                    return
                if h.kv_export is None:
                    self._reply_error(
                        503, "HandoffExportFailed",
                        "prefill finished but the page export failed; retry",
                        True,
                    )
                    return
                payload = h.kv_export
                self._reply(200, {
                    "first_token": payload.get("first_token"),
                    "prompt_len": payload["prompt_len"],
                    "handoff": payload,
                })
            except engine_mod.EngineRestarted as e:
                self._busy(str(e), err_type=type(e).__name__)
            except engine_mod.DeadlineExceeded as e:
                self._reply_error(504, type(e).__name__, str(e), False)
            except engine_mod.NonFiniteLogits as e:
                self._reply_error(500, type(e).__name__, str(e), False)
            except Exception as e:
                self._reply_error(
                    400, type(e).__name__, f"{type(e).__name__}: {e}", False
                )

        def do_POST(self):
            # trace context: join the caller's (router hop headers) or mint
            # a root — minting is always on so error bodies carry trace_id;
            # the serve.handle span id is pre-minted so engine stage spans
            # can parent on it before the handle span itself completes
            ctx = _obs.ctx_from_headers(self.headers)
            self._trace_id = ctx[0] if ctx else _obs.new_trace_id()
            self._handle_sid = _obs.new_span_id()
            self._err = None
            self._idem_key = None
            t0 = _time.perf_counter()
            try:
                self._do_post()
            finally:
                key = getattr(self, "_idem_key", None)
                if key is not None and idem is not None:
                    # the handler died without replying: wake joiners with
                    # no response so their keyed retries re-execute
                    self._idem_key = None
                    idem.abandon(key)
                _obs.record(
                    "serve.handle", self._trace_id,
                    t0=t0, t1=_time.perf_counter(),
                    span_id=self._handle_sid,
                    parent_id=(ctx[1] if ctx else None),
                    status="error" if self._err else "ok",
                    path=self.path, error=self._err,
                )

        def _do_post(self):
            if state["draining"]:
                self._busy("server draining, retry elsewhere",
                           err_type="Draining")
                return
            if self.path == "/generate" and engine is not None:
                key = self.headers.get("X-Idempotency-Key")
                if key and idem is not None:
                    verdict, val = idem.begin(key)
                    if verdict == "done":
                        status, body, hdrs = val
                        self._reply(status, body, headers={
                            **(hdrs or {}), "X-Idempotency-Replay": "hit",
                        })
                        return
                    if verdict == "join":
                        resp = idem.wait(val)
                        if resp is not None:
                            status, body, hdrs = resp
                            self._reply(status, body, headers={
                                **(hdrs or {}), "X-Idempotency-Replay": "join",
                            })
                            return
                        self._busy("idempotent join aborted; retry with the "
                                   "same key")
                        return
                    self._idem_key = key  # first sight: generate, then cache
                self._generate_engine()
                return
            if self.path == "/prefill" and engine is not None:
                self._prefill_engine()
                return
            if self.path == "/reserve" and engine is not None:
                self._reserve_engine()
                return
            if self.path == "/generate" and isinstance(predictor, GenerationPredictor):
                if not gate.acquire(blocking=False):
                    self._busy()
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n))
                    with lock:
                        toks = predictor.generate(
                            req["input_ids"],
                            max_new_tokens=req.get("max_new_tokens"),
                            temperature=req.get("temperature", 0.0),
                            eos_token_id=req.get("eos_token_id"),
                        )
                    self._reply(200, {"tokens": toks.tolist()})
                except Exception as e:
                    self._reply(400, {"error": f"{type(e).__name__}: {e}"})
                finally:
                    gate.release()
                return
            if self.path != "/predict" or not isinstance(predictor, Predictor):
                self._reply(404, {"error": "use POST /predict or /generate"})
                return
            if not gate.acquire(blocking=False):
                self._busy()
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n))
                avals = predictor._exported.in_avals
                # cast to each traced input's dtype (ids models take ints)
                arrays = [
                    np.asarray(x, avals[i].dtype if i < len(avals) else np.float32)
                    for i, x in enumerate(req["inputs"])
                ]
                with lock:  # one executable; serialize callers
                    outs = predictor.run(arrays)
                self._reply(200, {"outputs": [o.tolist() for o in outs]})
            except Exception as e:
                self._reply(400, {"error": f"{type(e).__name__}: {e}"})
            finally:
                gate.release()

    server = ThreadingHTTPServer((host, port), Handler)

    # -- graceful drain (SIGTERM / programmatic) ----------------------------
    prev_handler = {}

    def _restore_handler():
        if _signal.SIGTERM in prev_handler:
            try:
                _signal.signal(_signal.SIGTERM, prev_handler.pop(_signal.SIGTERM))
            except (ValueError, KeyError):
                pass

    def drain(grace=None):
        """Stop admitting (503 + Retry-After), let in-flight work finish up
        to `grace` seconds (PADDLE_STOP_GRACE env — exported by
        distributed.launch --stop_grace — else FLAGS_serve_drain_grace),
        then stop supervisor, engine, and HTTP loop.  Idempotent; returns
        the worker thread so callers can join it."""
        if state["draining"]:
            return state.get("drain_thread")
        state["draining"] = True
        if grace is None:
            grace = float(
                os.environ.get(
                    "PADDLE_STOP_GRACE", _fcore.flag("FLAGS_serve_drain_grace")
                )
            )

        def _worker():
            # a drain is the process's last orderly moment — persist the
            # flight ring before in-flight work winds down and we exit
            try:
                _flight.dump("serve-drain")
            except Exception:
                pass
            if engine is not None:
                engine.drain()
                deadline = _time.monotonic() + float(grace)
                while engine.has_work() and _time.monotonic() < deadline:
                    _time.sleep(0.02)
            if supervisor is not None:
                supervisor.stop()
            if engine is not None:
                engine.stop()
            server.shutdown()
            _restore_handler()

        t = threading.Thread(target=_worker, name="serve-drain", daemon=True)
        state["drain_thread"] = t
        t.start()
        return t

    server.drain = drain
    server.supervisor = supervisor
    server.engine = engine

    # SIGTERM → drain: installable only from the main thread; default to
    # trying when the caller did not say (tests spawn serve() off-thread and
    # silently skip, launched serving ranks run on main and get it)
    if handle_signals or handle_signals is None:
        try:
            prev_handler[_signal.SIGTERM] = _signal.signal(
                _signal.SIGTERM, lambda signum, frame: drain()
            )
        except ValueError:
            if handle_signals:
                raise

    if block:
        try:
            server.serve_forever()
        finally:
            _restore_handler()
        return server
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server


def __getattr__(name):
    # engine symbols load lazily: paddle_tpu/__init__ imports this module
    # during package init, before the model stack the engine depends on
    if name in (
        "ContinuousBatchingEngine", "EngineRequest", "QueueFull",
        "EngineUnavailable", "DeadlineUnattainable", "DeadlineExceeded",
        "RequestCancelled", "EngineRestarted", "NonFiniteLogits",
        "ContextOverflow",
    ):
        from . import engine as _engine

        return getattr(_engine, name)
    if name == "SessionStore":
        from .paging import SessionStore

        return SessionStore
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
