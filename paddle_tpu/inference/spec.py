"""Prompt-lookup drafting for speculative decoding (ISSUE 11).

The paged engine's verify step (engine.py `_verify_paged_body`) checks k
drafted tokens plus the committed last token in ONE compiled forward; this
module is the host half that produces the drafts.  There is no second
model: the drafter is pure n-gram lookup over the slot's OWN history
(prompt + everything generated so far), the classic prompt-lookup trick —
exactly the shared-system-prompt / template-heavy traffic the prefix cache
already optimizes for is the traffic whose continuations repeat.

Greedy equivalence does not depend on draft quality: the verify step
accepts draft i only while it equals the target model's own greedy
continuation, so a bad draft costs wasted FLOPs (positions the step would
otherwise leave idle — decode is HBM-bound, they are nearly free), never a
wrong token.  The drafter therefore optimizes hit rate only.

Everything here is host-side Python state, one instance per engine slot,
mutated only by the scheduler thread that owns the slot (under the
engine's `_mu`, like the rest of the slot table).  Nothing is traced:
draft CONTENT rides the compiled verify step as data (`toks[slots, k+1]`,
`valid_len[slots]`), so acceptance-rate churn never changes a shape.
"""

from __future__ import annotations


class NgramDrafter:
    """Per-slot prompt-lookup drafter.

    Indexes every n-gram (n = max_ngram .. 1) of the history as it grows;
    `propose(k)` matches the history's current n-token suffix against the
    latest earlier occurrence and returns the tokens that followed it —
    the continuation bet — longest order first, at most k tokens, possibly
    none.  A history shorter than max_ngram simply backs off to the orders
    that fit (a one-token prompt can still draft from 1-gram matches).

    The index keeps the latest TWO occurrence positions per n-gram: the
    most recent occurrence of the current suffix is always the suffix
    itself (empty continuation), so lookup falls back to the previous one.
    """

    def __init__(self, max_ngram=3):
        self.max_ngram = max(1, int(max_ngram))
        self.tokens = []
        # order -> {ngram tuple -> (previous_start, latest_start)} where a
        # "start" is the index of the token FOLLOWING that occurrence
        self._index = {n: {} for n in range(1, self.max_ngram + 1)}

    def __len__(self):
        return len(self.tokens)

    def reset(self, history):
        """Rebuild from scratch (prefill landing, warm restart re-admission):
        `history` is the prompt plus any already-emitted tokens."""
        self.tokens = []
        self._index = {n: {} for n in range(1, self.max_ngram + 1)}
        for t in history:
            self.extend(t)
        return self

    def extend(self, tok):
        """Append one committed token and index the n-grams it completes.
        O(max_ngram) per token — negligible next to a decode dispatch."""
        self.tokens.append(int(tok))
        end = len(self.tokens)
        for n in range(1, self.max_ngram + 1):
            if end < n:
                break
            d = self._index[n]
            key = tuple(self.tokens[end - n:end])
            prev = d.get(key)
            d[key] = (prev[1] if prev is not None else None, end)

    def propose(self, k):
        """Up to `k` draft tokens continuing the current history, longest
        matching n-gram first; [] when no earlier occurrence exists."""
        k = int(k)
        if k <= 0 or not self.tokens:
            return []
        L = len(self.tokens)
        for n in range(min(self.max_ngram, L), 0, -1):
            slot = self._index[n].get(tuple(self.tokens[L - n:]))
            if slot is None:
                continue
            for j in slot[::-1]:  # latest occurrence first, then previous
                if j is not None and j < L:
                    if j + k <= L:
                        return self.tokens[j:j + k]
                    # The match sits p = L - j tokens from the end: the
                    # continuation bet IS "the stream is periodic with
                    # period p", so extrapolate the cycle to the full k
                    # instead of truncating the draft.  Period 1 (constant
                    # runs, the greedy attractor of temperature-0 decode)
                    # would otherwise cap every window at 1 draft.
                    p = L - j
                    return [self.tokens[j + (i % p)] for i in range(k)]
        return []
