"""AMP (reference: python/paddle/amp/ auto_cast.py + grad_scaler.py).

TPU-native: bfloat16 is the default AMP dtype (no loss scaling needed —
GradScaler degrades to a pass-through when scaling is unnecessary, matching
the reference's bf16 behavior); fp16+dynamic loss scaling kept for parity.
O1 casting happens inside the op dispatcher via per-op white/black lists
(ops/dispatch.py amp_cast_inputs — the analogue of the reference's
AmpAutoCasts in eager codegen).
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from .. import ops
from ..framework import core as _core
from ..nn.layer import Layer
from ..ops.dispatch import apply, coerce
from ..tensor import Tensor

WHITE_LIST = {"matmul", "linear", "conv1d", "conv2d", "conv3d", "conv2d_transpose", "bmm", "mm", "einsum", "flash_attention"}
BLACK_LIST = {"softmax", "log_softmax", "layer_norm", "batch_norm", "cross_entropy", "nll_loss", "mean", "sum", "exp", "log", "pow"}


class AmpState:
    def __init__(self, enabled, dtype, level, custom_white_list=None, custom_black_list=None):
        self.enabled = enabled
        self.dtype = dtype
        self.level = level
        self.white = set(WHITE_LIST) | set(custom_white_list or ())
        self.black = set(BLACK_LIST) | set(custom_black_list or ())


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1", dtype="bfloat16", use_promote=True):
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"amp level must be O0/O1/O2, got {level}")
    state = AmpState(enable and level != "O0", dtype, level, custom_white_list, custom_black_list)
    old = _core.set_active_amp(state if state.enabled else None)
    try:
        yield
    finally:
        _core.set_active_amp(old)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16", master_weight=None, save_dtype=None):
    """O2: cast matmul-heavy params to the AMP dtype, keep norms fp32
    (reference: paddle.amp.decorate pure-fp16 with master weights)."""
    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models)
    if level == "O1":
        return (models, optimizers) if optimizers is not None else models
    target = _core.to_jax_dtype(dtype)

    from ..nn.norm import _BatchNormBase, GroupNorm, LayerNorm, RMSNorm, SpectralNorm

    keep_fp32 = (_BatchNormBase, GroupNorm, LayerNorm, RMSNorm, SpectralNorm)

    for model in model_list:
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, keep_fp32):
                continue
            for pname, p in layer._parameters.items():
                if p is not None and p.dtype == "float32":
                    p._data = p._data.astype(target)

    if optimizers is not None:
        opts = [optimizers] if not isinstance(optimizers, (list, tuple)) else list(optimizers)
        for opt in opts:
            use_master = master_weight is None or master_weight
            if use_master:
                opt._multi_precision = True
                for p in opt._all_params():
                    if p.dtype in ("float16", "bfloat16") and opt._key(p) not in opt._master_weights:
                        opt._master_weights[opt._key(p)] = Tensor(
                            p._data.astype(jnp.float32), stop_gradient=True
                        )

    # Scope dispatch-level O2 casting to each decorated model's forward:
    # white-listed ops cast inputs to the AMP dtype and black-listed ops
    # (softmax/CE/norm stats) get fp32 inputs.  Without this, a decorated
    # model relied on param dtypes alone and any fp32 leak (e.g. a norm
    # weight) silently promoted the whole residual stream to fp32 — the
    # round-1 bench OOM.  Wrapping forward (rather than setting a process
    # global) keeps other models in the process at their own numerics; an
    # explicit auto_cast(...) inside still takes precedence.
    state = AmpState(True, dtype, "O2")
    for model in model_list:
        if getattr(model, "_amp_decorated", False):
            continue
        orig_forward = model.forward

        def amp_forward(*args, __orig=orig_forward, **kwargs):
            old = _core.set_active_amp(state)
            try:
                return __orig(*args, **kwargs)
            finally:
                _core.set_active_amp(old)

        model.forward = amp_forward
        model._amp_decorated = True

    if optimizers is not None:
        return (models, optimizers)
    return models


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py).

    Works eagerly AND inside @to_static: found_inf, the loss scale, and the
    good/bad step counters are device state (Tensors), the skip-on-inf is a
    jnp.where select over every optimizer state write, and the scale/counter
    update is on-device arithmetic — so the whole fp16 train step compiles
    into one XLA program with no host round-trip (the reference reaches the
    same with update_loss_scaling_op in the static graph).
    """

    def __init__(
        self,
        enable=True,
        init_loss_scaling=2.0**15,
        incr_ratio=2.0,
        decr_ratio=0.5,
        incr_every_n_steps=2000,
        decr_every_n_nan_or_inf=1,
        use_dynamic_loss_scaling=True,
    ):
        import jax

        self._enable = enable
        with jax.ensure_compile_time_eval():
            self._scale = Tensor(jnp.asarray(init_loss_scaling, jnp.float32))
            self._good_steps = Tensor(jnp.asarray(0, jnp.int32))
            self._bad_steps = Tensor(jnp.asarray(0, jnp.int32))
        for t in (self._scale, self._good_steps, self._bad_steps):
            _core.unmark_born(t)  # persistent even if constructed mid-trace
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._found_inf = None  # None | Tensor(bool scalar) — eager cycle
        # whether the LAST completed update() cycle skipped the step on an
        # inf/nan — fault.Supervisor reads this to count scaler-skipped
        # steps against its non-finite budget without re-scanning grads
        self.last_found_inf = False
        # per-optimizer step state: INIT -> UNSCALED -> STEPPED, reset by
        # update() (reference: OptimizerState in python/paddle/amp/
        # grad_scaler.py).  Overloading _found_inf for this caused the
        # round-1 double-unscale bug: False is both "no inf found" and
        # "unscale_ not yet called".  Weak keys: a scaler outliving its
        # optimizers must not pin them (round-2 id()-keying leaked).
        import weakref

        self._optimizer_states = weakref.WeakKeyDictionary()
        # Traced cycles are namespaced PER TRACE PHASE (keyed weakly by the
        # trace token): @to_static runs the fn twice (discover + execute),
        # and host state carried across phases would make the execute pass
        # see the discover pass's STEPPED markers / leaked tracers.
        self._trace_cycles = weakref.WeakKeyDictionary()
        self._pending_traced_update = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale._data = jnp.asarray(v, jnp.float32)

    def scale(self, var):
        if not self._enable:
            return var
        return apply(lambda a, s: a * s.astype(a.dtype), [coerce(var), self._scale], name="scale_loss")

    # -- cycle state (eager: on self; traced: per trace token) -------------
    class _Cycle:
        __slots__ = ("states", "found")

        def __init__(self):
            self.states = {}  # id(optimizer) -> INIT/UNSCALED/STEPPED
            self.found = None

    def _cycle(self):
        tr = _core.active_trace()
        if tr is None:
            return None
        c = self._trace_cycles.get(tr)
        if c is None:
            c = GradScaler._Cycle()
            self._trace_cycles[tr] = c
        return c

    def _get_state(self, optimizer):
        c = self._cycle()
        if c is not None:
            return c.states.get(id(optimizer), "INIT")
        return self._optimizer_states.get(optimizer, "INIT")

    def _set_state(self, optimizer, st):
        c = self._cycle()
        if c is not None:
            c.states[id(optimizer)] = st
        else:
            self._optimizer_states[optimizer] = st

    def _get_found(self):
        c = self._cycle()
        return c.found if c is not None else self._found_inf

    def _set_found(self, v):
        c = self._cycle()
        if c is not None:
            c.found = v
        else:
            self._found_inf = v

    def unscale_(self, optimizer):
        if not self._enable:
            return
        st = self._get_state(optimizer)
        if st == "UNSCALED":
            raise RuntimeError(
                "unscale_() has already been called on this optimizer since "
                "the last update()."
            )
        if st == "STEPPED":
            raise RuntimeError("unscale_() must be called before step().")
        self._set_state(optimizer, "UNSCALED")
        pgs = optimizer._params_grads
        if not pgs:
            return
        inv = apply(lambda s: 1.0 / s, [self._scale])
        finite_flags = []
        for (p, g) in pgs:
            new_g = apply(
                lambda a, iv: a * iv.astype(a.dtype), [coerce(g), inv], name="unscale"
            )
            p.grad = new_g
            finite_flags.append(
                apply(lambda a: jnp.all(jnp.isfinite(a.astype(jnp.float32))), [new_g.detach()])
            )
        all_finite = finite_flags[0]
        for fl in finite_flags[1:]:
            all_finite = apply(lambda a, b: jnp.logical_and(a, b), [all_finite, fl])
        found_now = apply(lambda a: jnp.logical_not(a), [all_finite], name="found_inf")
        prev = self._get_found()
        if prev is None:
            self._set_found(found_now)
        else:
            # multi-optimizer pattern: a later unscale_ must not erase an
            # earlier optimizer's detection
            self._set_found(
                apply(lambda a, b: jnp.logical_or(a, b), [prev, found_now])
            )

    def step(self, optimizer):
        """Reference contract: scaler.step(opt) then scaler.update() —
        step() skips the update when an inf/nan was found and does NOT
        adjust the scale itself."""
        if not self._enable:
            optimizer.step()
            return
        st = self._get_state(optimizer)
        if st == "STEPPED":
            raise RuntimeError(
                "step() has already been called since the last update()."
            )
        if st == "INIT":
            self.unscale_(optimizer)
        found = self._get_found()
        if found is None:
            optimizer.step()  # no grads were unscaled (empty param list)
        elif _is_tracing():
            self._guarded_step(optimizer, found)
            self._pending_traced_update = True
        elif bool(found.numpy()):
            pass  # skip: inf/nan in grads
        else:
            optimizer.step()
        self._set_state(optimizer, "STEPPED")

    def _guarded_step(self, optimizer, found):
        """Traced skip-on-inf: run the update, then select old-vs-new for
        every optimizer state write with jnp.where(found_inf, old, new) —
        the whole thing stays inside the compiled program (lax.select, no
        host branch)."""
        # Accumulators are fully materialized by the time the EXECUTE phase
        # (the pass whose jaxpr becomes the program) runs — the discover
        # phase already ran the same Python and created them at their init
        # values — so this snapshot covers every state write, including a
        # skipped first step leaving fresh moments at init.
        snap = [
            (p, p._data)
            for p in optimizer._all_params()
            if not p.stop_gradient
        ]
        snap += [(t, t._data) for t in optimizer._master_weights.values()]
        snap += [(t, t._data) for t in optimizer._accumulators.values()]
        optimizer.step()
        skip = found._data
        for t, old in snap:
            new = t._data
            if new is not old:
                t._data = jnp.where(skip, old, new)

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def update(self):
        if not self._enable:
            return
        c = self._cycle()
        found = c.found if c is not None else self._found_inf
        if found is not None:
            import jax

            if not isinstance(found._data, jax.core.Tracer):
                self.last_found_inf = bool(found.numpy())
        if c is None and found is None and self._pending_traced_update:
            self._pending_traced_update = False  # one-shot: eager cycles resume
            raise RuntimeError(
                "scaler.step() ran inside a @to_static function but "
                "scaler.update() was called outside it; with compiled steps, "
                "call update() inside the same compiled function so the "
                "scale/counters update on-device."
            )
        if self._dynamic and found is not None:
            incr_r, decr_r = self._incr_ratio, self._decr_ratio
            incr_n, decr_n = self._incr_every, self._decr_every

            def f(found, scale, good, bad):
                bad_n = jnp.where(found, bad + 1, jnp.zeros_like(bad))
                good_n = jnp.where(found, jnp.zeros_like(good), good + 1)
                dec = bad_n >= decr_n
                inc = good_n >= incr_n
                scale_n = jnp.where(
                    dec, scale * decr_r, jnp.where(inc, scale * incr_r, scale)
                )
                bad_n = jnp.where(dec, jnp.zeros_like(bad_n), bad_n)
                good_n = jnp.where(inc, jnp.zeros_like(good_n), good_n)
                return scale_n, good_n, bad_n

            s, gd, bd = apply(
                f,
                [found, self._scale, self._good_steps, self._bad_steps],
                multi=True,
                name="update_loss_scaling",
            )
            self._scale._data = s._data
            self._good_steps._data = gd._data
            self._bad_steps._data = bd._data
        if c is not None:
            c.found = None
            c.states.clear()
            self._pending_traced_update = False
        else:
            self._found_inf = None
            self._optimizer_states.clear()

    def state_dict(self):
        return {
            "scale": self._scale.numpy(),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
            "good_steps": int(self._good_steps.numpy()),
            "bad_steps": int(self._bad_steps.numpy()),
        }

    def load_state_dict(self, state):
        import numpy as np

        self._scale._data = jnp.asarray(np.asarray(state["scale"]), jnp.float32)
        self._good_steps._data = jnp.asarray(state.get("good_steps", 0), jnp.int32)
        self._bad_steps._data = jnp.asarray(state.get("bad_steps", 0), jnp.int32)


def _is_tracing():
    return _core.active_trace() is not None


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True


class debugging:
    @staticmethod
    def enable_operator_stats_collection():
        pass

    @staticmethod
    def disable_operator_stats_collection():
        pass
