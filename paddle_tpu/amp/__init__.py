"""AMP (reference: python/paddle/amp/ auto_cast.py + grad_scaler.py).

TPU-native: bfloat16 is the default AMP dtype (no loss scaling needed —
GradScaler degrades to a pass-through when scaling is unnecessary, matching
the reference's bf16 behavior); fp16+dynamic loss scaling kept for parity.
O1 casting happens inside the op dispatcher via per-op white/black lists
(ops/dispatch.py amp_cast_inputs — the analogue of the reference's
AmpAutoCasts in eager codegen).
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from .. import ops
from ..framework import core as _core
from ..nn.layer import Layer
from ..ops.dispatch import apply, coerce
from ..tensor import Tensor

WHITE_LIST = {"matmul", "linear", "conv1d", "conv2d", "conv3d", "conv2d_transpose", "bmm", "mm", "einsum", "flash_attention"}
BLACK_LIST = {"softmax", "log_softmax", "layer_norm", "batch_norm", "cross_entropy", "nll_loss", "mean", "sum", "exp", "log", "pow"}


class AmpState:
    def __init__(self, enabled, dtype, level, custom_white_list=None, custom_black_list=None):
        self.enabled = enabled
        self.dtype = dtype
        self.level = level
        self.white = set(WHITE_LIST) | set(custom_white_list or ())
        self.black = set(BLACK_LIST) | set(custom_black_list or ())


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1", dtype="bfloat16", use_promote=True):
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"amp level must be O0/O1/O2, got {level}")
    state = AmpState(enable and level != "O0", dtype, level, custom_white_list, custom_black_list)
    old = _core.set_active_amp(state if state.enabled else None)
    try:
        yield
    finally:
        _core.set_active_amp(old)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16", master_weight=None, save_dtype=None):
    """O2: cast matmul-heavy params to the AMP dtype, keep norms fp32
    (reference: paddle.amp.decorate pure-fp16 with master weights)."""
    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models)
    if level == "O1":
        return (models, optimizers) if optimizers is not None else models
    target = _core.to_jax_dtype(dtype)

    from ..nn.norm import _BatchNormBase, GroupNorm, LayerNorm, RMSNorm

    keep_fp32 = (_BatchNormBase, GroupNorm, LayerNorm, RMSNorm)

    for model in model_list:
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, keep_fp32):
                continue
            for pname, p in layer._parameters.items():
                if p is not None and p.dtype == "float32":
                    p._data = p._data.astype(target)

    if optimizers is not None:
        opts = [optimizers] if not isinstance(optimizers, (list, tuple)) else list(optimizers)
        for opt in opts:
            use_master = master_weight is None or master_weight
            if use_master:
                opt._multi_precision = True
                for p in opt._all_params():
                    if p.dtype in ("float16", "bfloat16") and opt._key(p) not in opt._master_weights:
                        opt._master_weights[opt._key(p)] = Tensor(
                            p._data.astype(jnp.float32), stop_gradient=True
                        )

    # Scope dispatch-level O2 casting to each decorated model's forward:
    # white-listed ops cast inputs to the AMP dtype and black-listed ops
    # (softmax/CE/norm stats) get fp32 inputs.  Without this, a decorated
    # model relied on param dtypes alone and any fp32 leak (e.g. a norm
    # weight) silently promoted the whole residual stream to fp32 — the
    # round-1 bench OOM.  Wrapping forward (rather than setting a process
    # global) keeps other models in the process at their own numerics; an
    # explicit auto_cast(...) inside still takes precedence.
    state = AmpState(True, dtype, "O2")
    for model in model_list:
        if getattr(model, "_amp_decorated", False):
            continue
        orig_forward = model.forward

        def amp_forward(*args, __orig=orig_forward, **kwargs):
            old = _core.set_active_amp(state)
            try:
                return __orig(*args, **kwargs)
            finally:
                _core.set_active_amp(old)

        model.forward = amp_forward
        model._amp_decorated = True

    if optimizers is not None:
        return (models, optimizers)
    return models


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py)."""

    def __init__(
        self,
        enable=True,
        init_loss_scaling=2.0**15,
        incr_ratio=2.0,
        decr_ratio=0.5,
        incr_every_n_steps=2000,
        decr_every_n_nan_or_inf=1,
        use_dynamic_loss_scaling=True,
    ):
        self._enable = enable
        self._scale = Tensor(jnp.asarray(init_loss_scaling, jnp.float32))
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = None
        # per-optimizer step state: INIT -> UNSCALED -> STEPPED, reset by
        # update() (reference: OptimizerState in python/paddle/amp/
        # grad_scaler.py).  Overloading _found_inf for this caused the
        # round-1 double-unscale bug: False is both "no inf found" and
        # "unscale_ not yet called".
        self._optimizer_states = {}

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale._data = jnp.asarray(v, jnp.float32)

    def scale(self, var):
        if not self._enable:
            return var
        return apply(lambda a, s: a * s.astype(a.dtype), [coerce(var), self._scale], name="scale_loss")

    def unscale_(self, optimizer):
        if not self._enable:
            return
        st = self._optimizer_states.get(id(optimizer), "INIT")
        if st == "UNSCALED":
            raise RuntimeError(
                "unscale_() has already been called on this optimizer since "
                "the last update()."
            )
        if st == "STEPPED":
            raise RuntimeError("unscale_() must be called before step().")
        self._optimizer_states[id(optimizer)] = "UNSCALED"
        pgs = optimizer._params_grads
        if not pgs:
            return
        inv = apply(lambda s: 1.0 / s, [self._scale])
        finite_flags = []
        for (p, g) in pgs:
            new_g = apply(
                lambda a, iv: a * iv.astype(a.dtype), [coerce(g), inv], name="unscale"
            )
            p.grad = new_g
            finite_flags.append(
                apply(lambda a: jnp.all(jnp.isfinite(a.astype(jnp.float32))), [new_g.detach()])
            )
        all_finite = finite_flags[0]
        for fl in finite_flags[1:]:
            all_finite = apply(lambda a, b: jnp.logical_and(a, b), [all_finite, fl])
        if _is_tracing():
            # traced flag; step() rejects this until the compiled-scaler path
            self._found_inf = all_finite
        else:
            found = not bool(all_finite.numpy())
            # OR with any inf already found this cycle (multi-optimizer
            # pattern: a later unscale_ must not erase an earlier optimizer's
            # detection)
            prev = self._found_inf if isinstance(self._found_inf, bool) else False
            self._found_inf = prev or found
        return

    def step(self, optimizer):
        """Reference contract: scaler.step(opt) then scaler.update() —
        step() skips the update when an inf/nan was found and does NOT
        adjust the scale itself."""
        if not self._enable:
            optimizer.step()
            return
        st = self._optimizer_states.get(id(optimizer), "INIT")
        if st == "STEPPED":
            raise RuntimeError(
                "step() has already been called since the last update()."
            )
        if st == "INIT":
            self.unscale_(optimizer)
        if isinstance(self._found_inf, Tensor):
            raise RuntimeError(
                "GradScaler with dynamic host-side skipping is not supported inside "
                "@to_static; use bf16 AMP (no scaler) for compiled steps."
            )
        if not self._found_inf:
            optimizer.step()
        self._optimizer_states[id(optimizer)] = "STEPPED"

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def update(self):
        if not self._enable:
            return
        if self._dynamic:
            if self._found_inf:
                self._bad_steps += 1
                self._good_steps = 0
                if self._bad_steps >= self._decr_every:
                    self._scale._data = self._scale._data * self._decr_ratio
                    self._bad_steps = 0
            else:
                self._good_steps += 1
                self._bad_steps = 0
                if self._good_steps >= self._incr_every:
                    self._scale._data = self._scale._data * self._incr_ratio
                    self._good_steps = 0
        self._found_inf = None
        self._optimizer_states = {}

    def state_dict(self):
        return {
            "scale": self._scale.numpy(),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, state):
        import numpy as np

        self._scale._data = jnp.asarray(np.asarray(state["scale"]), jnp.float32)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


def _is_tracing():
    return _core.active_trace() is not None


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True


class debugging:
    @staticmethod
    def enable_operator_stats_collection():
        pass

    @staticmethod
    def disable_operator_stats_collection():
        pass
