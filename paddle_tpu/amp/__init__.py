# placeholder during bring-up
