"""paddle_tpu — a TPU-native deep learning framework with a Paddle-shaped API.

Built from scratch on JAX/XLA/Pallas (see SURVEY.md for the blueprint mapping
to the reference batizty/Paddle): dygraph eager execution over XLA's op cache,
tape autograd powered by jax.vjp, whole-train-step compilation via
paddle_tpu.jit.to_static, GSPMD/mesh-based hybrid parallelism under a
Fleet-style API, and Pallas kernels for the attention hot path.
"""

from __future__ import annotations

from . import framework
from .framework import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    TPUPlace,
    get_default_dtype,
    get_device,
    get_flags,
    is_compiled_with_cuda,
    seed,
    set_default_dtype,
    set_device,
    set_flags,
    get_rng_state,
    set_rng_state,
)
from .tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .ops import *  # noqa: F401,F403
from . import ops
from . import autograd
from .autograd import grad, no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa: F401
# Bring-up note: submodule imports are appended as each subsystem lands.
from . import nn  # noqa: E402
from . import optimizer
from . import amp
from . import io
from . import jit
from . import vision
from . import distributed
from . import metric
from . import device
from . import profiler
from . import incubate
from . import sparse
from . import fft
from . import distribution
from . import signal
from . import regularizer
from . import version  # noqa: F401
from .version import full_version as __version__  # noqa: F401
from . import static
from . import inference
from . import serving  # noqa: F401  (multi-replica router + failover)
from . import fault  # noqa: F401  (fault injection + supervised recovery)
from .framework.io import save, load  # noqa: F401
from .jit import to_static  # noqa: F401
from .hapi import Model  # noqa: F401
from . import hapi as callbacks  # noqa: F401  (paddle.callbacks namespace)

# make `from paddle_tpu.callbacks import X` importable, not just attribute
# access (the reference ships callbacks as a real submodule)
import sys as _sys

_sys.modules[__name__ + ".callbacks"] = callbacks
from .distributed import DataParallel  # noqa: F401
from . import models  # noqa: F401

# dtype name constants (paddle.float32 is a dtype spec string here)
float16 = "float16"
bfloat16 = "bfloat16"
float32 = "float32"
float64 = "float64"
int8 = "int8"
int16 = "int16"
int32 = "int32"
int64 = "int64"
uint8 = "uint8"
bool = "bool"
complex64 = "complex64"
complex128 = "complex128"



def disable_static(place=None):
    """Dygraph is the default; kept for API compat."""


def enable_static():
    raise NotImplementedError(
        "paddle_tpu is dygraph-first; use paddle_tpu.jit.to_static for compiled "
        "execution (the static-graph path maps onto XLA step compilation)."
    )


def in_dynamic_mode():
    return True


def is_grad_enabled_():
    return framework.core.grad_enabled()


def summary(net, input_size=None, dtypes=None):
    total = sum(p.size for p in net.parameters())
    trainable = sum(p.size for p in net.parameters() if not p.stop_gradient)
    print(f"Total params: {total}\nTrainable params: {trainable}")
    return {"total_params": total, "trainable_params": trainable}
