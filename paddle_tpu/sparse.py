"""Sparse tensors (reference: paddle.sparse — SparseCooTensor /
SparseCsrTensor over PHI sparse kernels, paddle/phi/kernels/sparse/,
SURVEY.md §2.1 "PHI tensor core").

TPU-native: backed by jax.experimental.sparse.BCOO — the batched-COO
format XLA can lower on TPU (gather/scatter + segment reductions on dense
tiles), so sparse ops compose with jit/grad rather than needing custom
CUDA kernels.  The API mirrors the reference subset that matters for
training: construction, to_dense/to_sparse round trips, elementwise
add/mul, relu, sparse @ dense matmul, and value transforms.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from .ops.dispatch import coerce, wrap
from .tensor import Tensor


class SparseCooTensor:
    """COO sparse tensor (reference: paddle.sparse.sparse_coo_tensor).

    Holds a BCOO; `.indices()` / `.values()` / `.to_dense()` follow the
    reference API.  Dense-result ops return paddle Tensors.
    """

    def __init__(self, bcoo):
        self._bcoo = bcoo

    # -- construction -----------------------------------------------------
    @staticmethod
    def from_dense(x):
        x = coerce(x)
        return SparseCooTensor(jsparse.BCOO.fromdense(x._data))

    def to_sparse_csr(self):
        return to_sparse_csr(self)

    # -- reference surface ------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        from .framework import core as _core

        return _core.convert_dtype(self._bcoo.dtype)

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return wrap(jnp.transpose(self._bcoo.indices))  # [ndim, nnz] like paddle

    def values(self):
        return wrap(self._bcoo.data)

    def to_dense(self):
        return wrap(self._bcoo.todense())

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    # -- math -------------------------------------------------------------
    def __add__(self, other):
        if isinstance(other, SparseCooTensor):
            return SparseCooTensor((self._bcoo + other._bcoo).sum_duplicates())
        return wrap(self._bcoo.todense() + coerce(other)._data)

    def __mul__(self, scalar):
        return SparseCooTensor(
            jsparse.BCOO((self._bcoo.data * scalar, self._bcoo.indices), shape=self._bcoo.shape)
        )

    def matmul(self, dense):
        """sparse [m, k] @ dense [k, n] -> dense Tensor [m, n]."""
        d = coerce(dense)
        return wrap(self._bcoo @ d._data)

    def transpose(self, perm=None):
        ndim = len(self._bcoo.shape)
        perm = perm or list(reversed(range(ndim)))
        idx = self._bcoo.indices[:, jnp.asarray(perm)]
        shape = tuple(self._bcoo.shape[p] for p in perm)
        return SparseCooTensor(jsparse.BCOO((self._bcoo.data, idx), shape=shape))

    def _map_values(self, fn):
        return SparseCooTensor(
            jsparse.BCOO((fn(self._bcoo.data), self._bcoo.indices), shape=self._bcoo.shape)
        )

    def __repr__(self):
        return f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, dtype={self.dtype})"


class SparseCsrTensor:
    """CSR sparse tensor (reference: paddle.sparse.sparse_csr_tensor /
    SparseCsrTensor over phi sparse CSR kernels).  Backed by
    jax.experimental.sparse.BCSR; `.crows()` / `.cols()` / `.values()`
    follow the reference API (2-D only, the reference's common case)."""

    def __init__(self, bcsr):
        self._bcsr = bcsr

    @staticmethod
    def from_dense(x):
        x = coerce(x)
        return SparseCsrTensor(jsparse.BCSR.fromdense(x._data))

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        from .framework import core as _core

        return _core.convert_dtype(self._bcsr.dtype)

    @property
    def nnz(self):
        return int(self._bcsr.nse)

    def crows(self):
        return wrap(self._bcsr.indptr)

    def cols(self):
        return wrap(self._bcsr.indices)

    def values(self):
        return wrap(self._bcsr.data)

    def to_dense(self):
        return wrap(self._bcsr.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._bcsr.to_bcoo())

    def matmul(self, dense):
        """sparse [m, k] @ dense [k, n] -> dense Tensor [m, n]."""
        d = coerce(dense)
        return wrap(self._bcsr @ d._data)

    def _map_values(self, fn):
        return SparseCsrTensor(
            jsparse.BCSR(
                (fn(self._bcsr.data), self._bcsr.indices, self._bcsr.indptr),
                shape=self._bcsr.shape,
            )
        )

    def __add__(self, other):
        if isinstance(other, SparseCsrTensor):
            # route through BCOO (BCSR has no direct add), back to CSR
            s = (self._bcsr.to_bcoo() + other._bcsr.to_bcoo()).sum_duplicates()
            return SparseCsrTensor(jsparse.BCSR.from_bcoo(s))
        return wrap(self._bcsr.todense() + coerce(other)._data)

    def __mul__(self, scalar):
        return self._map_values(lambda v: v * scalar)

    def __repr__(self):
        return f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, dtype={self.dtype})"


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    """Build from CSR triplets (reference signature)."""
    indptr = coerce(crows)._data.astype(jnp.int32)
    indices = coerce(cols)._data.astype(jnp.int32)
    vals = coerce(values)._data
    if dtype is not None:
        from .framework import core as _core

        vals = vals.astype(_core.to_jax_dtype(dtype))
    return SparseCsrTensor(jsparse.BCSR((vals, indices, indptr), shape=tuple(shape)))


def to_sparse_csr(x):
    if isinstance(x, SparseCooTensor):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(x._bcoo.sum_duplicates()))
    return SparseCsrTensor.from_dense(x)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    """Build from [ndim, nnz] indices + [nnz] values (reference signature)."""
    idx = coerce(indices)._data.astype(jnp.int32)
    vals = coerce(values)._data
    if dtype is not None:
        from .framework import core as _core

        vals = vals.astype(_core.to_jax_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) + 1 for i in np.asarray(jnp.max(idx, axis=1)))
    return SparseCooTensor(jsparse.BCOO((vals, jnp.transpose(idx)), shape=tuple(shape)))


def to_sparse_coo(x, sparse_dim=None):
    return SparseCooTensor.from_dense(x)


def to_dense(x):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        return x.to_dense()
    return coerce(x)


def add(a, b):
    return a + b


def matmul(a, b):
    if isinstance(a, (SparseCooTensor, SparseCsrTensor)):
        return a.matmul(b)
    return coerce(a).matmul(coerce(b))


def masked_matmul(x, y, mask):
    """dense @ dense, sampled at `mask`'s sparsity pattern (reference:
    paddle.sparse.masked_matmul — the SDDMM kernel)."""
    x, y = coerce(x), coerce(y)
    idx = mask._bcoo.indices  # [nnz, 2]
    rows = x._data[idx[:, 0]]
    cols = y._data[:, idx[:, 1]].T
    vals = jnp.sum(rows * cols, axis=-1)
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape))


class nn:
    """paddle.sparse.nn subset."""

    class ReLU:
        def __call__(self, x):
            return relu(x)


def relu(x):
    return x._map_values(lambda v: jnp.maximum(v, 0))


def sqrt(x):
    return x._map_values(jnp.sqrt)


def sin(x):
    return x._map_values(jnp.sin)


def tanh(x):
    return x._map_values(jnp.tanh)
