"""ctypes bridge to the native core (libpaddle_tpu_core.so, built from
csrc/ — the framework's C++ runtime layer: flag registry, host staging
arena, host tracer, TCPStore rendezvous, batch staging engine).

The build is auto-attempted once (cmake+ninja, quiet) and every consumer
degrades gracefully to a pure-Python path when the library is unavailable,
so the framework works on machines without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CSRC = os.path.join(_ROOT, "csrc")
_BUILD = os.path.join(_CSRC, "build")
_LIBNAME = "libpaddle_tpu_core.so"
# installed wheel layout: the .so is bundled inside the package dir
_PKG_LIB = os.path.join(os.path.dirname(os.path.abspath(__file__)), _LIBNAME)

_lib = None
_tried = False
_lock = threading.Lock()


def _try_build():
    if not os.path.isdir(_CSRC):
        return None
    try:
        subprocess.run(
            ["cmake", "-B", _BUILD, "-G", "Ninja", "-DCMAKE_BUILD_TYPE=Release"],
            cwd=_CSRC, capture_output=True, timeout=120, check=True,
        )
        subprocess.run(
            ["ninja", "-C", _BUILD, "paddle_tpu_core"],
            capture_output=True, timeout=300, check=True,
        )
    except Exception:
        return None
    path = os.path.join(_BUILD, _LIBNAME)
    return path if os.path.exists(path) else None


def get_lib():
    """Returns the loaded CDLL or None."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = _PKG_LIB if os.path.exists(_PKG_LIB) else os.path.join(_BUILD, _LIBNAME)
        if not os.path.exists(path):
            path = _try_build()
        if not path:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        # signatures
        lib.pt_host_alloc.restype = ctypes.c_void_p
        lib.pt_host_alloc.argtypes = [ctypes.c_size_t]
        lib.pt_host_free.argtypes = [ctypes.c_void_p]
        lib.pt_host_bytes_in_use.restype = ctypes.c_int64
        lib.pt_host_peak_bytes.restype = ctypes.c_int64
        lib.pt_host_bytes_reserved.restype = ctypes.c_int64
        lib.pt_host_alloc_count.restype = ctypes.c_int64
        lib.pt_trace_begin.restype = ctypes.c_int64
        lib.pt_trace_begin.argtypes = [ctypes.c_char_p]
        lib.pt_trace_end.argtypes = [ctypes.c_int64]
        lib.pt_trace_mark.argtypes = [ctypes.c_char_p]
        lib.pt_trace_export_chrome.argtypes = [ctypes.c_char_p]
        lib.pt_trace_event_count.restype = ctypes.c_int64
        lib.pt_flag_define_bool.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.pt_flag_define_int.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
        lib.pt_flag_define_double.argtypes = [ctypes.c_char_p, ctypes.c_double]
        lib.pt_flag_define_string.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.pt_flag_set.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.pt_flag_get_bool.argtypes = [ctypes.c_char_p]
        lib.pt_flag_get_int.argtypes = [ctypes.c_char_p]
        lib.pt_flag_get_int.restype = ctypes.c_longlong
        lib.pt_flag_get_double.argtypes = [ctypes.c_char_p]
        lib.pt_flag_get_double.restype = ctypes.c_double
        lib.pt_flag_get_string.argtypes = [ctypes.c_char_p]
        lib.pt_flag_get_string.restype = ctypes.c_char_p
        lib.pt_store_server_start.restype = ctypes.c_void_p
        lib.pt_store_server_start.argtypes = [ctypes.c_int]
        lib.pt_store_server_port.restype = ctypes.c_int
        lib.pt_store_server_port.argtypes = [ctypes.c_void_p]
        lib.pt_store_server_stop.argtypes = [ctypes.c_void_p]
        lib.pt_store_connect.restype = ctypes.c_void_p
        lib.pt_store_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.pt_store_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.pt_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.pt_store_add.restype = ctypes.c_longlong
        lib.pt_store_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
        lib.pt_store_check.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pt_store_close.argtypes = [ctypes.c_void_p]
        lib.pt_stage_create.restype = ctypes.c_void_p
        lib.pt_stage_create.argtypes = [ctypes.c_int]
        lib.pt_stage_destroy.argtypes = [ctypes.c_void_p]
        lib.pt_stage_submit.restype = ctypes.c_void_p
        lib.pt_stage_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ]
        lib.pt_stage_ready.argtypes = [ctypes.c_void_p]
        lib.pt_stage_buffer.restype = ctypes.c_void_p
        lib.pt_stage_buffer.argtypes = [ctypes.c_void_p]
        lib.pt_stage_release.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available():
    return get_lib() is not None


# ---------------------------------------------------------------------------
# convenience wrappers
# ---------------------------------------------------------------------------


def host_memory_stats():
    lib = get_lib()
    if lib is None:
        return {}
    return {
        "host_bytes_in_use": lib.pt_host_bytes_in_use(),
        "host_peak_bytes": lib.pt_host_peak_bytes(),
        "host_bytes_reserved": lib.pt_host_bytes_reserved(),
        "host_alloc_count": lib.pt_host_alloc_count(),
    }


class TCPStore:
    """Rendezvous KV store over the native server (reference: paddle TcpStore).

    is_master=True starts the server in-process; all ranks connect as clients.
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False, world_size=None, timeout=None):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native core library unavailable; build csrc/ first")
        self._lib = lib
        self._server = None
        if is_master:
            self._server = lib.pt_store_server_start(port)
            if not self._server:
                raise RuntimeError(f"failed to bind TCPStore on port {port}")
            port = lib.pt_store_server_port(self._server)
        self.host = host
        self.port = port
        self._client = lib.pt_store_connect(host.encode(), port)
        if not self._client:
            raise RuntimeError(f"failed to connect TCPStore {host}:{port}")

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        self._lib.pt_store_set(self._client, key.encode(), value, len(value))

    def get(self, key, cap=1 << 16):
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.pt_store_get(self._client, key.encode(), buf, cap)
        if n < 0:
            raise RuntimeError(f"TCPStore get({key!r}) failed")
        return buf.raw[:n]

    def add(self, key, delta):
        out = self._lib.pt_store_add(self._client, key.encode(), delta)
        if out < 0:
            # counters are non-negative by construction; -1 means the
            # connection died (e.g. the master exited) — surface it instead
            # of letting callers supervise forever against a dead store
            raise RuntimeError(f"TCPStore add({key!r}) failed: connection lost")
        return out

    def check(self, key):
        return bool(self._lib.pt_store_check(self._client, key.encode()))

    def wait(self, keys):
        for k in keys if isinstance(keys, (list, tuple)) else [keys]:
            self.get(k)

    def barrier(self, name, world_size):
        n = self.add(f"__barrier__{name}", 1)
        if n == world_size:
            self.set(f"__barrier__{name}__done", "1")
        self.get(f"__barrier__{name}__done")

    def close(self):
        if self._client:
            self._lib.pt_store_close(self._client)
            self._client = None
        if self._server:
            self._lib.pt_store_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class BatchStage:
    """Native gather engine for DataLoader fast path: rows of a contiguous
    numpy array gathered into arena buffers by C++ threads (GIL-free)."""

    def __init__(self, num_workers=2):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native core library unavailable")
        self._lib = lib
        self._h = lib.pt_stage_create(num_workers)

    def gather(self, array, indices):
        """array: 2D+ C-contiguous np array; indices: int list → new np array."""
        import numpy as np

        arr = np.ascontiguousarray(array)
        row_bytes = arr.dtype.itemsize * int(np.prod(arr.shape[1:]))
        idx = np.asarray(indices, np.int64)
        c_idx = idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        job = self._lib.pt_stage_submit(
            self._h, arr.ctypes.data_as(ctypes.c_void_p), row_bytes, c_idx, len(idx)
        )
        import time

        while not self._lib.pt_stage_ready(job):
            time.sleep(0)
        buf = self._lib.pt_stage_buffer(job)
        out_shape = (len(idx),) + arr.shape[1:]
        out = np.ctypeslib.as_array(
            ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8)), (row_bytes * len(idx),)
        ).view(arr.dtype).reshape(out_shape).copy()
        self._lib.pt_stage_release(job)
        return out

    def close(self):
        if self._h:
            self._lib.pt_stage_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class RecordEventNative:
    """Host tracer span via the native recorder (chrome-trace exportable)."""

    def __init__(self, name):
        self.name = name.encode()
        self._id = -1

    def __enter__(self):
        lib = get_lib()
        if lib is not None:
            self._id = lib.pt_trace_begin(self.name)
        return self

    def __exit__(self, *exc):
        lib = get_lib()
        if lib is not None:
            lib.pt_trace_end(self._id)
        return False


def trace_enable(on=True):
    lib = get_lib()
    if lib is not None:
        lib.pt_trace_enable(1 if on else 0)


def trace_export(path):
    lib = get_lib()
    if lib is not None:
        return lib.pt_trace_export_chrome(path.encode())
    return -1
