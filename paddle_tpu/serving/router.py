"""Multi-replica serving router (ISSUE 9): health-checked failover,
deadline propagation, and rolling drain.

One `Router` owns a registry of N engine replicas (each a `serve()`
instance, optionally a router-managed `ReplicaProcess`).  A probe thread
GETs every replica's `/healthz` on `FLAGS_router_probe_interval`, tracking
live/ready/draining/dead plus the load gauges the engine exports (queue
depth, drain estimate, page-pool free fraction, EWMA decode step time).

Routing contract:

- **Bounded admission**: at most `FLAGS_router_max_inflight` requests in
  flight through the router; beyond that, brownout — shed with 503 and a
  `Retry-After` derived from the HEALTHIEST replica's drain estimate
  (clamped by the request's own deadline).
- **Deadline propagation**: the client's `X-Deadline-Ms` (or body
  `deadline_s`) becomes an absolute deadline at arrival; every hop forwards
  only the REMAINING budget, so a downstream `DeadlineUnattainable` stays
  meaningful and a spent budget 504s without touching a replica.  A
  deadline'd request that no ready replica can meet (drain estimates all
  exceed the remaining budget) is shed FIRST — over-deadline work never
  queues behind feasible work.
- **Failover, exactly-once**: on connect failure, 503, or a retriable
  typed error (`EngineRestarted`, `DeadlineUnattainable` — a less-loaded
  replica may still meet it), ZERO-TOKEN requests retry on another replica
  with jittered exponential backoff, bounded by `FLAGS_router_max_retries`
  and the remaining deadline.  The retry decision is header/field-driven
  (`retriable` + `Retry-After` from serve()'s typed error JSON), never
  string-matched.  Once response bytes have crossed (a token-bearing
  stream), the request fails typed (`UpstreamIncomplete`, 502,
  retriable=false) — a retry could double-deliver.
- **Circuit breaker** per replica: closed -> open after
  `FLAGS_router_breaker_threshold` consecutive failures -> half-open (one
  trial after `FLAGS_router_breaker_cooldown`) -> closed on success.
- **Hedging** (off by default): with `FLAGS_router_hedge_s > 0`, a
  zero-token request still unanswered after the hedge delay is duplicated
  onto a second replica; the first complete response wins (generation is
  pure, so the abandoned duplicate is harmless).
- **Rolling drain/restart**: `rolling_restart()` takes replicas one at a
  time — admin-drain (router stops picking it), wait for in-flight work to
  finish up to the grace window, restart through the launch `Container`
  (SIGTERM -> grace -> SIGKILL -> respawn), and re-admit only after
  `/healthz` reports ready.  Zero dropped requests: the fleet keeps
  serving through the survivor(s).

- **Disaggregated pipeline** (ISSUE 19): when the fleet has BOTH a ready
  prefill-role and a ready decode-role replica, single-prompt adapterless
  requests route through `/reserve` (decode pages held up front) ->
  `/prefill` (chunked prefill + 1 token + page export) -> `/generate`
  with the handoff payload on the decode worker.  `pick_pair()` scores
  prefill workers on compute backlog and decode workers on page headroom;
  zero-free-page decode workers are hard-skipped (typed
  `NoDecodeCapacity` 503 when none is left).  Every hop failure before
  decode bytes cross is a zero-token retriable failover — deterministic
  prefill makes the retry's final tokens bit-identical — and abandoned
  reservations expire by TTL on the decode side.

- **Session affinity** (ISSUE 20): requests carrying a ``session_id``
  pin to the replica holding that session's committed KV pages (the one
  that last answered a turn for it).  The pin is advisory: a dead,
  draining, or breaker-open pinned replica is unpinned and the turn falls
  back to a normal pick — the new replica re-prefills the conversation
  statelessly, answers bit-identically, and becomes the new pin.
  Session requests never take the disaggregated pipeline (their KV is
  replica-resident by construction).

Chaos: `router.replica.hang` wedges one dispatch (bounded by the HTTP
timeout), `router.replica.flap` fails probes, `router.replica.kill`
SIGKILLs a managed replica at probe time, `disagg.prefill.crash` /
`disagg.handoff.drop` kill the handoff mid-pipeline — all armed through
the same `FLAGS_fault_inject` registry production uses.

Crash-proof front door (ISSUE 17): with a `journal=` the router writes
every breaker transition, registry/drain decision, and idempotency
outcome into `serving.journal.Journal` (append-only, checksummed,
atomic-rename segments) and beats a rank-0 heartbeat from its probe
loop.  Requests carrying an `X-Idempotency-Key` dedupe against a TTL'd
completed-response cache with an in-flight join — a client retry after a
connection reset can never produce two generations.  `router.crash`
(kill -9 drill) stops the heartbeat; a `RouterStandby` detects the stale
seq on ITS OWN clock, replays the journal (repairing a torn tail),
restores breakers so they don't re-close onto sick replicas, re-probes
the fleet, and resumes serving — takeover state machine: WATCHING ->
TAKING_OVER -> SERVING.
"""

from __future__ import annotations

import json
import random
import threading
import time

from .. import profiler as _prof
from ..framework import core as _core
from ..obs import flight as _flight
from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs
from .journal import IdempotencyCache, Journal
from .replica import Replica, ReplicaTransportError


def _count_by_value(mapping):
    out = {}
    for v in mapping.values():
        out[v] = out.get(v, 0) + 1
    return out


class RouterError(RuntimeError):
    """Typed router-level failure (carries its HTTP mapping)."""

    status = 500
    retriable = False

    def __init__(self, msg, retry_after_s=None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class NoReadyReplica(RouterError):
    status = 503
    retriable = True


class RouterOverloaded(RouterError):
    status = 503
    retriable = True


class NoDecodeCapacity(RouterError):
    """Disaggregated serving (ISSUE 19): every decode-role worker is
    page-starved (zero free pages), so the pipeline has nowhere to seat a
    handoff.  503 + Retry-After — page headroom frees as streams finish,
    so the shed is retriable by design."""

    status = 503
    retriable = True


class DeadlineExhausted(RouterError):
    status = 504
    retriable = False


class RouterCrashed(RuntimeError):
    """The router process is dead (the `router.crash` kill -9 drill): an
    in-process caller sees this exception where an HTTP client would see a
    connection reset — never a typed response.  The contract for callers:
    resubmit the SAME idempotency key against the successor router; dedupe
    (router- and replica-side) guarantees at most one generation."""


class Router:
    """Front-end router over N serve() replicas.  Thread-safe: handler
    threads call `handle_generate()` concurrently with the probe thread
    and the rolling-restart orchestrator; router-local mutable state is
    guarded by `self._mu` (per-replica state lives under each Replica's
    own lock)."""

    def __init__(self, replicas, probe_interval=None, probe_timeout=None,
                 max_retries=None, retry_backoff=None, max_inflight=None,
                 hedge_s=None, seed=0, journal=None, heartbeat=None,
                 idem_ttl=None):
        self.replicas = [
            r if isinstance(r, Replica) else Replica(f"r{i}", r)
            for i, r in enumerate(replicas)
        ]
        if len({r.rid for r in self.replicas}) != len(self.replicas):
            raise ValueError("replica ids must be unique")
        f = _core.flag
        self.probe_interval = float(
            probe_interval if probe_interval is not None
            else f("FLAGS_router_probe_interval"))
        self.probe_timeout = float(
            probe_timeout if probe_timeout is not None
            else f("FLAGS_router_probe_timeout"))
        self.max_retries = int(
            max_retries if max_retries is not None
            else f("FLAGS_router_max_retries"))
        self.retry_backoff = float(
            retry_backoff if retry_backoff is not None
            else f("FLAGS_router_retry_backoff"))
        self.max_inflight = int(
            max_inflight if max_inflight is not None
            else f("FLAGS_router_max_inflight"))
        self.hedge_s = float(
            hedge_s if hedge_s is not None else f("FLAGS_router_hedge_s"))
        self.idem_ttl = float(
            idem_ttl if idem_ttl is not None else f("FLAGS_router_idem_ttl"))
        self._retry_after_jitter = float(f("FLAGS_router_retry_after_jitter"))
        self._mu = threading.Lock()
        self._rng = random.Random(seed)  # jitter; accessed under _mu
        self._inflight = 0
        # session -> replica pinning (ISSUE 20): a session's committed KV
        # pages live on exactly one replica, so later turns route back to
        # it.  Advisory, not durable — a dead pin falls back to a normal
        # pick and the new replica re-prefills statelessly, so the pin map
        # never needs journaling and exactly-once is untouched.
        self._session_pins = {}  # sid -> rid; accessed under _mu
        self._stop = threading.Event()
        self._probe_thread = None
        self._crashed = False
        self._takeovers = 0
        # crash-proof front door (ISSUE 17): journal = durable control
        # plane (a path string opens/replays one), heartbeat = rank-0
        # liveness the standby watches (a path string starts a writer)
        self.journal = (
            journal if journal is None or isinstance(journal, Journal)
            else Journal(journal)
        )
        if heartbeat is None or not isinstance(heartbeat, str):
            self._heartbeat = heartbeat
        else:
            from ..fault import heartbeat as _hb

            self._heartbeat = _hb.HeartbeatWriter(heartbeat, rank=0,
                                                  interval=0.0)
        self._idem = IdempotencyCache(self.idem_ttl, journal=self.journal)
        if self.journal is not None:
            self._bootstrap_from_journal()

    def _bootstrap_from_journal(self):
        """With a FRESH journal, seed it with the fleet registry.  With a
        RESUMED journal (this router is the successor after a takeover),
        rehydrate first: re-create journaled replicas missing from the
        registry, restore breaker state (so the successor does not re-close
        onto a replica the primary already knew was sick), drain flags, and
        the completed-response idempotency entries; the autoscaler picks its
        band/cooldown clocks out of the same state.  Journal binding to the
        replicas happens LAST so restoration itself is never re-journaled."""
        j = self.journal
        resumed = j.resumed
        st = j.state_snapshot() if resumed else None
        if resumed:
            t0 = time.perf_counter()
            reps = list(self.replicas)
            known = {r.rid for r in reps}
            for rid, info in st["replicas"].items():
                if rid not in known:
                    reps.append(Replica(rid, info["url"]))
            with self._mu:
                self.replicas = reps
            by_rid = {r.rid: r for r in reps}
            for rid, info in st["replicas"].items():
                rep = by_rid.get(rid)
                if rep is not None and info.get("draining"):
                    rep.set_admin_draining(True)
            for rid, b in st["breakers"].items():
                rep = by_rid.get(rid)
                if rep is not None:
                    rep.restore_breaker(
                        b["breaker"], b["fails"], b["open_until_wall"]
                    )
            restored = self._idem.restore(st["idem"])
            with self._mu:
                self._takeovers = int(st["takeovers"]) + 1
                takeovers = self._takeovers
            j.append("takeover")
            _prof.record_router_event("takeovers")
            _flight.record(
                "router",
                f"takeover #{takeovers}: journal replayed to seq {st['seq']}",
                replicas=len(reps), breakers=len(st["breakers"]),
                idem_restored=restored,
            )
            _obs.record(
                "router.takeover", _obs.new_trace_id(), t0=t0,
                t1=time.perf_counter(), status="ok", takeovers=takeovers,
                journal_seq=st["seq"],
            )
        for rep in self.replicas:
            if not resumed or rep.rid not in st["replicas"]:
                j.append("replica", op="register", rid=rep.rid,
                         url=rep.base_url)
            rep.bind_journal(j)

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """First probe sweep synchronously (so pick() has state before any
        traffic), then the background probe loop."""
        with self._mu:
            if self._probe_thread is not None:
                return self
        self.probe_once()
        t = threading.Thread(
            target=self._probe_loop, name="router-probe", daemon=True
        )
        with self._mu:
            self._probe_thread = t
        t.start()
        return self

    def stop(self):
        self._stop.set()
        with self._mu:
            t = self._probe_thread
        if t is not None:
            t.join(5)

    def _probe_loop(self):
        while not self._stop.wait(self.probe_interval):
            self.probe_once()

    def probe_once(self):
        """One probe sweep over the registry (the probe thread's body;
        tests call it inline for deterministic drills)."""
        from ..fault import injection as _inj

        if _inj.should_fire("router.crash"):
            self._crash("injected router.crash")
            return
        for rep in self.replicas:
            if (rep.process is not None
                    and _inj.should_fire("router.replica.kill", context=rep.rid)):
                rep.process.kill9()
            if _inj.should_fire("router.replica.flap", context=rep.rid):
                rep.note_probe_failure("injected flap")
            else:
                rep.probe(timeout=self.probe_timeout)
            _prof.record_router_replica_state(rep.rid, rep.state)
        hb = self._heartbeat
        if hb is not None:
            try:
                # the heartbeat rides the probe loop: seq advancing means
                # the front door is both alive AND sweeping its fleet
                hb.beat()
            except OSError:
                pass

    def _crash(self, reason):
        """Model kill -9 of the front door (the router.crash drill): every
        in-flight and subsequent handle_generate raises RouterCrashed (the
        HTTP layer drops the connection — clients see a reset, never a
        typed response), the probe loop stops, and the heartbeat goes stale
        so a RouterStandby detects death on ITS OWN clock and takes over.
        The journal is NOT closed gracefully — a real SIGKILL wouldn't —
        which is exactly what the torn-tail repair path is for."""
        with self._mu:
            if self._crashed:
                return
            self._crashed = True
        self._stop.set()
        _prof.record_router_event("crashes")
        _flight.record("router", f"router crashed: {reason}")
        _flight.dump("router-crash")
        hb = self._heartbeat
        if hb is not None:
            hb.stop()

    def _check_crashed(self):
        with self._mu:
            crashed = self._crashed
        if crashed:
            raise RouterCrashed("router process is dead (kill -9 drill)")

    # -- registry (ISSUE 16: the autoscaler grows/shrinks the fleet live) ----

    def add_replica(self, rep):
        """Register one replica while traffic flows.  `self.replicas` is
        REPLACED (copy-on-write) under `_mu`, never mutated in place: pick/
        probe/healthz iterate whatever list object they captured, so a
        handler mid-scan sees a consistent (if momentarily stale) fleet.
        The new replica enters as 'connecting' — pick() ignores it until a
        probe reports ready, so no request lands on a cold boot."""
        rep = rep if isinstance(rep, Replica) else Replica(
            f"r{len(self.replicas)}", rep
        )
        with self._mu:
            if any(r.rid == rep.rid for r in self.replicas):
                raise ValueError(f"replica id {rep.rid!r} already registered")
            self.replicas = self.replicas + [rep]
        _prof.record_router_replica_state(rep.rid, rep.state)
        _flight.record("router", f"replica {rep.rid} registered",
                       url=rep.base_url, fleet=len(self.replicas))
        if self.journal is not None:
            self.journal.append("replica", op="register", rid=rep.rid,
                                url=rep.base_url)
            rep.bind_journal(self.journal)
        return rep

    def remove_replica(self, rid):
        """Deregister one replica (copy-on-write, see add_replica).  The
        handle is returned so the caller can terminate its process; the
        caller is responsible for having drained it first — the autoscaler
        rides the admin-drain path exactly like rolling_restart."""
        with self._mu:
            rep = next((r for r in self.replicas if r.rid == rid), None)
            if rep is None:
                raise KeyError(f"no replica with id {rid!r}")
            self.replicas = [r for r in self.replicas if r.rid != rid]
        _prof.record_router_replica_state(rep.rid, "removed")
        _flight.record("router", f"replica {rep.rid} deregistered",
                       fleet=len(self.replicas))
        if self.journal is not None:
            self.journal.append("replica", op="deregister", rid=rep.rid)
        return rep

    # -- selection -----------------------------------------------------------

    def pick(self, exclude=(), adapter=None):
        """Least-loaded ready replica whose breaker admits traffic: score by
        (adapter residency, drain estimate, queued+active work, EWMA
        latency).  When the request names a LoRA adapter, replicas whose
        last probe reported it resident sort FIRST — a miss is still
        eligible (every replica loads on demand at admission), it just only
        wins when every resident replica is excluded or breaker-gated.
        Breaker gates are consumed in score order so a half-open trial slot
        is only spent on the replica actually chosen.

        Page-starved replicas (zero free KV pages) are SKIPPED outright
        while any alternative exists — not merely down-scored, because a
        request landed on one parks until a stream finishes — and only
        reconsidered when they are the whole fleet (ISSUE 19)."""
        cands = []
        starved = []
        for i, rep in enumerate(self.replicas):
            if rep.rid in exclude:
                continue
            s = rep.snapshot()
            if s["state"] != "ready" or s["admin_draining"]:
                continue
            miss = 0 if not adapter else int(adapter not in s["lora_adapters"])
            key = (
                miss,
                s["drain_estimate_s"],
                s["queue_depth"] + s["active_slots"],
                s["ewma_latency_s"],
                i,
                rep,
            )
            (starved if s["page_free_frac"] <= 0.0 else cands).append(key)
        for *_, rep in sorted(cands, key=lambda c: c[:5]):
            if rep.allow():
                return rep
        for *_, rep in sorted(starved, key=lambda c: c[:5]):
            if rep.allow():
                return rep
        return None

    def _pinned_replica(self, sid, tried):
        """Resolve a session pin to a usable replica, or None.

        A usable pin is a registered replica that is ready, not draining,
        not already tried this dispatch, and whose breaker admits traffic.
        Anything else UNPINS the session (recorded as a repin) and returns
        None — the caller falls back to a normal pick() and the winning
        replica re-prefills the conversation statelessly, then becomes the
        new pin on success."""
        with self._mu:
            rid = self._session_pins.get(sid)
        if rid is None:
            return None
        rep = next((r for r in self.replicas if r.rid == rid), None)
        usable = False
        if rep is not None and rid not in tried:
            s = rep.snapshot()
            usable = (s["state"] == "ready" and not s["admin_draining"]
                      and rep.allow())
        if usable:
            _prof.record_router_event("session_pin_hits")
            return rep
        with self._mu:
            if self._session_pins.get(sid) == rid:
                del self._session_pins[sid]
        _prof.record_router_event("session_repins")
        _flight.record(
            "session", "pin broken, falling back to stateless re-prefill",
            session_id=sid, pinned_rid=rid,
            reason="gone" if rep is None else "unavailable",
        )
        return None

    def _pin_session(self, sid, rid):
        with self._mu:
            prev = self._session_pins.get(sid)
            self._session_pins[sid] = rid
        if prev != rid:
            _flight.record("session", "pinned", session_id=sid, rid=rid)

    def pick_pair(self, exclude_prefill=(), exclude_decode=()):
        """(prefill, decode) pair for the disaggregated pipeline (ISSUE 19).

        Prefill workers are scored on COMPUTE backlog — drain estimate,
        queued+active work, EWMA latency — because a prefill hop is one
        bounded burst of compute.  Decode workers are scored on PAGE
        headroom first (most free pages wins), then drain estimate: the
        handoff's cost there is seated residency, not compute.  A decode
        worker with zero free pages is hard-skipped — never down-scored —
        and when EVERY decode worker is page-starved the pipeline raises
        the typed `NoDecodeCapacity` (503 + Retry-After) instead of
        parking the request.  Either side with no ready replica at all
        returns None in its slot (the caller falls back to the colocated
        path).  Breaker gates are consumed in score order, like pick()."""
        pre_c, dec_c = [], []
        dec_starved = False
        for i, rep in enumerate(self.replicas):
            s = rep.snapshot()
            if s["state"] != "ready" or s["admin_draining"]:
                continue
            role = s.get("role", "colocated")
            if role == "prefill" and rep.rid not in exclude_prefill:
                pre_c.append((
                    s["drain_estimate_s"],
                    s["queue_depth"] + s["active_slots"],
                    s["ewma_latency_s"],
                    i,
                    rep,
                ))
            elif role == "decode" and rep.rid not in exclude_decode:
                if s["page_free_frac"] <= 0.0:
                    dec_starved = True
                    continue
                dec_c.append((
                    -s["page_free_frac"],
                    s["drain_estimate_s"],
                    s["queue_depth"] + s["active_slots"],
                    i,
                    rep,
                ))
        pre = next(
            (r for *_, r in sorted(pre_c, key=lambda c: c[:4]) if r.allow()),
            None,
        )
        dec = next(
            (r for *_, r in sorted(dec_c, key=lambda c: c[:4]) if r.allow()),
            None,
        )
        if dec is None and dec_starved:
            _prof.record_disagg_event("no_decode_capacity")
            _flight.record("disagg", "no decode capacity (all page-starved)")
            raise NoDecodeCapacity(
                "every decode worker is page-starved (zero free KV pages)",
                retry_after_s=self.healthiest_retry_after(),
            )
        return pre, dec

    def _ready_drains(self):
        return [
            s["drain_estimate_s"]
            for s in (rep.snapshot() for rep in self.replicas)
            if s["state"] == "ready" and not s["admin_draining"]
        ]

    def healthiest_retry_after(self, default=1.0):
        """Retry-After for a shed request: the smallest drain estimate over
        ready replicas (the soonest ANY replica plausibly frees up)."""
        drains = self._ready_drains()
        return max(default, min(drains)) if drains else default

    def healthz(self):
        snaps = [rep.snapshot() for rep in self.replicas]
        ready = sum(
            1 for s in snaps if s["state"] == "ready" and not s["admin_draining"]
        )
        with self._mu:
            inflight = self._inflight
            takeovers = self._takeovers
            session_pins = dict(self._session_pins)
        roles = {}
        for s in snaps:
            if s["state"] == "ready" and not s["admin_draining"]:
                role = s.get("role", "colocated")
                roles[role] = roles.get(role, 0) + 1
        return {
            "status": "ready" if ready else "degraded",
            "ready_replicas": ready,
            "roles": roles,
            "replicas": snaps,
            "inflight": inflight,
            "breakers": {s["id"]: s["breaker"] for s in snaps},
            "takeovers": takeovers,
            "journal_seq": self.journal.seq if self.journal is not None else None,
            "idempotency": self._idem.stats(),
            "session_pins": len(session_pins),
            "session_pins_by_replica": _count_by_value(session_pins),
        }

    # -- routing -------------------------------------------------------------

    def handle_generate(self, payload, deadline_ms=None, trace=None,
                        idem_key=None):
        """Route one /generate body.  Returns (status, body, headers);
        every request resolves exactly once — a success from exactly one
        replica, or ONE typed error.

        `idem_key` (or a body ``idempotency_key``, which is stripped before
        forwarding) engages the crash-proof front door: a key already
        completed within the TTL replays the stored response byte-identical
        (``X-Idempotency-Replay: hit``); a key currently in flight JOINS
        the live request instead of double-generating (``: join``); only a
        first sight executes.  Retriable outcomes (sheds, restarts) are
        never cached, so a later retry re-executes safely.

        `trace` is the client hop's `(trace_id, parent_span_id)` from
        ``X-Trace-Id``/``X-Parent-Span`` (or None: the router is the first
        hop and mints the trace id).  The whole handle is recorded as the
        ``router.admit`` root span; error bodies carry the trace id even
        when span recording is off."""
        if idem_key is None and isinstance(payload, dict):
            idem_key = payload.pop("idempotency_key", None)
        self._check_crashed()
        _prof.record_router_event("requests")
        if not idem_key:
            return self._handle_routed(payload, deadline_ms, trace, None)
        verdict, val = self._idem.begin(idem_key)
        if verdict == "done":
            return self._replayed(val, "hit")
        if verdict == "join":
            timeout = (
                max(0.05, float(deadline_ms) / 1e3)
                if deadline_ms is not None else 600.0
            )
            resp = self._idem.wait(val, timeout=timeout)
            self._check_crashed()
            if resp is not None:
                return self._replayed(resp, "join")
            return self._error(
                503, "IdempotentJoinAborted",
                f"in-flight request for key {idem_key!r} ended without a "
                "response; retry with the same key", True,
                self._jitter_retry_after(self.healthiest_retry_after()),
            )
        try:
            status, body, headers = self._handle_routed(
                payload, deadline_ms, trace, idem_key
            )
        except BaseException:
            self._idem.abandon(idem_key)
            raise
        with self._mu:
            crashed = self._crashed
        if crashed:
            # the router died while this request was in flight: the client
            # saw a reset, never these bytes.  Abandon the entry — any
            # completed generation is cached REPLICA-side, so the client's
            # resubmit through the successor replays it, not re-generates.
            self._idem.abandon(idem_key)
            raise RouterCrashed("router crashed mid-request")
        self._idem.complete(idem_key, status, body, headers)
        return status, body, headers

    @staticmethod
    def _replayed(resp, how):
        status, body, hdrs = resp
        headers = dict(hdrs or {})
        headers["X-Idempotency-Replay"] = how
        return status, body, headers

    def _handle_routed(self, payload, deadline_ms, trace, idem_key):
        tid = trace[0] if trace else _obs.new_trace_id()
        client_sid = trace[1] if trace else None
        admit_sid = _obs.new_span_id()  # pre-minted: children parent on it
        t_adm = time.perf_counter()
        deadline_t = (
            time.monotonic() + float(deadline_ms) / 1e3
            if deadline_ms is not None else None
        )
        with self._mu:
            admitted = self._inflight < self.max_inflight
            if admitted:
                self._inflight += 1
        if not admitted:
            _prof.record_router_event("brownout_sheds")
            _flight.record(
                "admission", "router gate full (brownout shed)",
                trace_id=tid, max_inflight=self.max_inflight,
            )
            ra = self._clamp_retry_after(
                self._jitter_retry_after(self.healthiest_retry_after()),
                deadline_t,
            )
            out = self._error(
                503, "RouterOverloaded", "router admission gate full", True,
                ra, trace_id=tid,
            )
            _obs.record(
                "router.admit", tid, t0=t_adm, t1=time.perf_counter(),
                span_id=admit_sid, parent_id=client_sid, status="error",
                error="RouterOverloaded",
            )
            return out
        try:
            status, body, headers = self._dispatch(
                payload, deadline_t, (tid, admit_sid), idem_key=idem_key
            )
        finally:
            with self._mu:
                self._inflight -= 1
        _obs.record(
            "router.admit", tid, t0=t_adm, t1=time.perf_counter(),
            span_id=admit_sid, parent_id=client_sid,
            status="ok" if status == 200 else "error", http_status=status,
            error=None if status == 200 else (body or {}).get("type"),
        )
        return status, body, headers

    def _dispatch(self, payload, deadline_t, trace, idem_key=None):
        if self._disagg_eligible(payload):
            return self._dispatch_disagg(payload, deadline_t, trace,
                                         idem_key=idem_key)
        return self._dispatch_colocated(payload, deadline_t, trace,
                                        idem_key=idem_key)

    def _disagg_eligible(self, payload):
        """The disaggregated pipeline engages only when the fleet has BOTH
        a ready prefill-role and a ready decode-role replica, for
        single-prompt requests without a LoRA adapter (the prefill worker's
        exported KV embeds no adapter deltas) — everything else rides the
        colocated path unchanged."""
        if not isinstance(payload, dict):
            return False
        if payload.get("adapter") or payload.get("handoff"):
            return False
        if payload.get("session_id"):
            # session KV is replica-resident state; the prefill/decode split
            # would strand the pinned pages on the wrong worker
            return False
        ids = payload.get("input_ids")
        if not ids or isinstance(ids[0], list):
            return False
        has_pre = has_dec = False
        for rep in self.replicas:
            s = rep.snapshot()
            if s["state"] != "ready" or s["admin_draining"]:
                continue
            role = s.get("role", "colocated")
            has_pre = has_pre or role == "prefill"
            has_dec = has_dec or role == "decode"
            if has_pre and has_dec:
                return True
        return False

    def _dispatch_disagg(self, payload, deadline_t, trace, idem_key=None):
        """Route one request through the disaggregated pipeline:

            /reserve (decode)  — hold the pages the stream will seat
            /prefill (prefill) — chunked prefill + 1 token + page export
            /generate (decode) — import the handoff, stream the rest

        Single-token requests (max_new_tokens <= 1) take the TTFT fast
        path: /prefill alone, no export, no reservation, no decode hop —
        the prefill worker's sampled token is the whole response.

        Every hop failure BEFORE the decode response completes is a
        zero-token failover: no client-visible tokens have crossed, so the
        whole pipeline retries on a fresh pair (deterministic prefill means
        the retry's final tokens are bit-identical).  An abandoned
        reservation is reclaimed by its TTL on the decode side.  Once
        decode response bytes have crossed, the colocated exactly-once
        rule applies unchanged (UpstreamIncomplete, never a blind retry)."""
        from ..fault import injection as _inj

        tid, admit_sid = trace
        tried_pre, tried_dec = set(), set()
        attempt = 0
        while True:
            remaining = (
                None if deadline_t is None else deadline_t - time.monotonic()
            )
            if remaining is not None and remaining <= 0:
                _prof.record_router_event("deadline_sheds")
                return self._error(
                    504, "DeadlineExhausted",
                    "deadline spent before the disagg pipeline completed",
                    False, trace_id=tid,
                )
            t_pick = time.perf_counter()
            try:
                pre, dec = self.pick_pair(tried_pre, tried_dec)
                if (pre is None or dec is None) and (tried_pre or tried_dec):
                    # every distinct pair member was tried; with budget
                    # left, allow a second pass (a respawn may be back)
                    tried_pre, tried_dec = set(), set()
                    pre, dec = self.pick_pair()
            except NoDecodeCapacity as e:
                _obs.record(
                    "disagg.pair", tid, t0=t_pick, t1=time.perf_counter(),
                    parent_id=admit_sid, attempt=attempt, status="error",
                    error="NoDecodeCapacity",
                )
                return self._error(
                    e.status, "NoDecodeCapacity", str(e), e.retriable,
                    self._clamp_retry_after(
                        self._jitter_retry_after(
                            e.retry_after_s
                            if e.retry_after_s is not None
                            else self.healthiest_retry_after()
                        ),
                        deadline_t,
                    ),
                    trace_id=tid,
                )
            _obs.record(
                "disagg.pair", tid, t0=t_pick, t1=time.perf_counter(),
                parent_id=admit_sid, attempt=attempt,
                prefill=pre.rid if pre is not None else None,
                decode=dec.rid if dec is not None else None,
                status="ok" if pre is not None and dec is not None else "error",
            )
            if pre is None or dec is None:
                # one side of the fleet dissolved mid-request: the
                # colocated path still serves (any role answers /generate)
                _flight.record(
                    "disagg", "pair incomplete; colocated fallback",
                    trace_id=tid,
                )
                return self._dispatch_colocated(
                    payload, deadline_t, trace, idem_key=idem_key
                )
            _prof.record_disagg_event("pair_picks")
            if attempt > 0:
                _prof.record_router_event("retries")
                _prof.record_disagg_event("handoff_retries")

            # single-token requests COMPLETE at the prefill hop: the
            # prefill worker's sampled token IS the whole response, so
            # no reservation is held and no handoff crosses — probe/TTFT
            # traffic never queues behind the decode worker's seated
            # streams (this is the disaggregation TTFT fast path)
            n_new = int(payload.get("max_new_tokens") or 32)
            single = n_new <= 1
            reservation = None

            if not single:
                # -- hop 1: reserve decode-side pages BEFORE prefill runs --
                status, body, headers, retriable = self._send(
                    dec,
                    {
                        "prompt_len": len(payload["input_ids"]),
                        "max_new_tokens": n_new,
                    },
                    remaining, trace, attempt=attempt,
                    path="/reserve", span="disagg.reserve",
                    partial_retriable=True,
                )
                if status != 200:
                    _prof.record_disagg_event("reserve_fails")
                    tried_dec.add(dec.rid)
                    if not retriable or attempt >= self.max_retries:
                        return status, body, headers
                    attempt = self._disagg_backoff(attempt, deadline_t)
                    if attempt is None:
                        return self._error(
                            504, "DeadlineExhausted",
                            "deadline spent during disagg failover", False,
                            trace_id=tid,
                        )
                    continue
                reservation = body.get("reservation")

            # -- hop 2: prefill + page export on the prefill worker --------
            status, body, headers, retriable = self._send(
                pre,
                {
                    "input_ids": payload["input_ids"],
                    "temperature": payload.get("temperature", 0.0),
                    "eos_token_id": payload.get("eos_token_id"),
                    "export": not single,
                },
                remaining, trace, attempt=attempt,
                path="/prefill", span="disagg.prefill",
                partial_retriable=True,
            )
            if status != 200:
                # zero tokens crossed: mid-handoff death (kill -9, crash
                # drill) is ALWAYS a retriable failover; the reservation
                # just made is left for its TTL to reclaim
                tried_pre.add(pre.rid)
                if not retriable or attempt >= self.max_retries:
                    return status, body, headers
                attempt = self._disagg_backoff(attempt, deadline_t)
                if attempt is None:
                    return self._error(
                        504, "DeadlineExhausted",
                        "deadline spent during disagg failover", False,
                        trace_id=tid,
                    )
                continue
            if single:
                # zero-token-to-client until here, so the usual failover
                # rules applied; now the prefill response IS the result
                return 200, {
                    "tokens": list(payload["input_ids"])
                    + [int(body["first_token"])],
                }, headers
            handoff = body.get("handoff")

            # -- handoff: the payload crosses router memory ----------------
            t_hand = time.perf_counter()
            try:
                _inj.inject(
                    "disagg.handoff.drop", context=f"{pre.rid}->{dec.rid}"
                )
            except _inj.InjectedFault as e:
                # the payload is gone in flight: neither replica failed, so
                # no breaker/tried bookkeeping — just retry the pipeline
                # from scratch (deterministic prefill -> identical retry)
                _obs.record(
                    "disagg.handoff", tid, t0=t_hand, t1=time.perf_counter(),
                    parent_id=admit_sid, attempt=attempt, status="error",
                    error=f"{e}",
                )
                _flight.record("disagg", f"handoff dropped: {e}",
                               trace_id=tid)
                attempt = self._disagg_backoff(attempt, deadline_t)
                if attempt is None:
                    return self._error(
                        504, "DeadlineExhausted",
                        "deadline spent during disagg failover", False,
                        trace_id=tid,
                    )
                continue
            if not isinstance(handoff, dict):
                tried_pre.add(pre.rid)
                if attempt >= self.max_retries:
                    return self._error(
                        502, "HandoffMissing",
                        f"prefill worker {pre.rid} answered without a "
                        "handoff payload", False, trace_id=tid,
                    )
                attempt = self._disagg_backoff(attempt, deadline_t)
                if attempt is None:
                    return self._error(
                        504, "DeadlineExhausted",
                        "deadline spent during disagg failover", False,
                        trace_id=tid,
                    )
                continue
            _obs.record(
                "disagg.handoff", tid, t0=t_hand, t1=time.perf_counter(),
                parent_id=admit_sid, attempt=attempt, status="ok",
                payload_bytes=handoff.get("payload_bytes"),
                prefill=pre.rid, decode=dec.rid,
            )

            # -- hop 3: import + decode on the decode worker ---------------
            fwd = {
                k: v for k, v in payload.items()
                if k not in ("handoff", "reservation")
            }
            fwd["handoff"] = handoff
            fwd["reservation"] = reservation
            remaining = (
                None if deadline_t is None else deadline_t - time.monotonic()
            )
            status, body, headers, retriable = self._send(
                dec, fwd, remaining, trace, attempt=attempt,
                idem_key=idem_key, span="disagg.decode",
            )
            if status == 200:
                return 200, body, headers
            tried_dec.add(dec.rid)
            if not retriable or attempt >= self.max_retries:
                return status, body, headers
            attempt = self._disagg_backoff(attempt, deadline_t)
            if attempt is None:
                return self._error(
                    504, "DeadlineExhausted",
                    "deadline spent during disagg failover", False,
                    trace_id=tid,
                )

    def _disagg_backoff(self, attempt, deadline_t):
        """Sleep the jittered backoff (clamped to half the remaining
        budget) and return the next attempt number — or None when the
        deadline is already spent, so callers shed instead of sleeping."""
        delay = self._backoff(attempt)
        if deadline_t is not None:
            remaining = deadline_t - time.monotonic()
            if remaining <= 0.01:
                _prof.record_router_event("deadline_sheds")
                return None
            delay = min(delay, remaining / 2)
        time.sleep(delay)
        return attempt + 1

    def _dispatch_colocated(self, payload, deadline_t, trace, idem_key=None):
        tid, admit_sid = trace
        tried = set()
        attempt = 0
        prev_rid = None
        while True:
            remaining = (
                None if deadline_t is None else deadline_t - time.monotonic()
            )
            if remaining is not None and remaining <= 0:
                _prof.record_router_event("deadline_sheds")
                return self._error(
                    504, "DeadlineExhausted",
                    "deadline spent before a replica answered", False,
                    trace_id=tid,
                )
            if remaining is not None:
                # brownout: shed over-deadline work FIRST — when every ready
                # replica's backlog already exceeds the remaining budget,
                # queueing this request anywhere only steals capacity from
                # feasible work
                drains = self._ready_drains()
                if drains and min(drains) > remaining:
                    _prof.record_router_event("brownout_sheds")
                    _flight.record(
                        "admission", "deadline-infeasible shed",
                        trace_id=tid, best_drain_s=round(min(drains), 3),
                        remaining_s=round(remaining, 3),
                    )
                    return self._error(
                        504, "DeadlineUnattainable",
                        f"no replica can meet the deadline (best drain "
                        f"estimate {min(drains):.2f}s > remaining "
                        f"{remaining:.2f}s)", False,
                        retry_after=self._jitter_retry_after(min(drains)),
                        trace_id=tid,
                    )
            t_pick = time.perf_counter()
            adapter = payload.get("adapter") if isinstance(payload, dict) else None
            sid = payload.get("session_id") if isinstance(payload, dict) else None
            rep = self._pinned_replica(sid, tried) if sid else None
            if rep is None:
                rep = self.pick(exclude=tried, adapter=adapter)
            if rep is None and tried:
                # every distinct replica was tried; with budget left, allow
                # a second pass (a restarted replica may be back)
                tried = set()
                rep = self.pick(adapter=adapter)
            _obs.record(
                "router.pick", tid, t0=t_pick, t1=time.perf_counter(),
                parent_id=admit_sid, attempt=attempt,
                picked=rep.rid if rep is not None else None,
                status="ok" if rep is not None else "error",
            )
            if rep is None:
                _prof.record_router_event("no_replica")
                _flight.record("admission", "no ready replica", trace_id=tid)
                ra = self._clamp_retry_after(
                    self._jitter_retry_after(self.healthiest_retry_after()),
                    deadline_t,
                )
                return self._error(
                    503, "NoReadyReplica",
                    "no ready replica (all down, draining, or breaker-open)",
                    True, ra, trace_id=tid,
                )
            if attempt > 0:
                _prof.record_router_event("retries")
                if rep.rid != prev_rid:
                    _prof.record_router_event("failovers")
            outcome = self._send_hedged(rep, payload, remaining, trace,
                                        attempt=attempt, idem_key=idem_key)
            status, body, headers, retriable = outcome
            if status == 200:
                if sid:
                    self._pin_session(sid, rep.rid)
                return 200, body, headers
            prev_rid = rep.rid
            tried.add(rep.rid)
            if not retriable or attempt >= self.max_retries:
                return status, body, headers
            delay = self._backoff(attempt)
            if remaining is not None:
                remaining = deadline_t - time.monotonic()
                if remaining <= 0.01:
                    _prof.record_router_event("deadline_sheds")
                    return self._error(
                        504, "DeadlineExhausted",
                        "deadline spent during failover", False,
                        trace_id=tid,
                    )
                delay = min(delay, remaining / 2)
            time.sleep(delay)
            attempt += 1

    def _backoff(self, attempt):
        """Jittered exponential backoff: base * 2^attempt * U(0.5, 1.5)."""
        with self._mu:
            jitter = 0.5 + self._rng.random()
        return self.retry_backoff * (2 ** attempt) * jitter

    def _send(self, rep, payload, remaining_s, trace, attempt=0,
              idem_key=None, path="/generate", span="replica.forward",
              partial_retriable=False):
        """One dispatch attempt.  Returns (status, body, headers, retriable)
        and folds the outcome into the replica's breaker/latency state.

        The forward span id is minted BEFORE the HTTP call so it can ride
        ``X-Parent-Span`` — the replica's ``serve.handle`` span parents on
        this attempt, and a dead attempt still leaves an ``aborted`` span
        joining the failure to the surviving retry.

        `path`/`span` route the disaggregated pipeline's /reserve and
        /prefill hops through the same breaker + span machinery.
        `partial_retriable=True` marks a hop that carries NO client-visible
        tokens: a connection that dies mid-response there is still a
        zero-token failover, where the /generate hop must fail typed
        (UpstreamIncomplete) once bytes have crossed."""
        tid, admit_sid = trace
        fwd_sid = _obs.new_span_id()
        t_fwd = time.perf_counter()
        try:
            # /generate keeps its dedicated entry point — instrumentation
            # and tests hook post_generate to observe client-visible
            # dispatches specifically
            if path == "/generate":
                status, body, headers, latency = rep.post_generate(
                    payload, remaining_s, trace=(tid, fwd_sid),
                    idem_key=idem_key,
                )
            else:
                status, body, headers, latency = rep.post_json(
                    path, payload, remaining_s, trace=(tid, fwd_sid),
                    idem_key=idem_key,
                )
        except ReplicaTransportError as e:
            _obs.record(
                span, tid, t0=t_fwd, t1=time.perf_counter(),
                span_id=fwd_sid, parent_id=admit_sid, status="aborted",
                replica=rep.rid, attempt=attempt, error=f"{e}",
            )
            rep.record_failure(str(e))
            if e.response_started and not partial_retriable:
                # bytes already reached us: a retry could double-deliver
                # tokens — fail typed instead (exactly-once)
                st, bd, hd = self._error(
                    502, "UpstreamIncomplete",
                    f"replica {rep.rid} died mid-response: {e}", False,
                    trace_id=tid,
                )
                return st, bd, hd, False
            st, bd, hd = self._error(
                503, "ReplicaUnreachable",
                f"replica {rep.rid} unreachable: {e}", True, trace_id=tid,
            )
            return st, bd, hd, True
        _obs.record(
            span, tid, t0=t_fwd, t1=time.perf_counter(),
            span_id=fwd_sid, parent_id=admit_sid,
            status="ok" if status == 200 else "error",
            replica=rep.rid, attempt=attempt, http_status=status,
        )
        if status == 200:
            rep.record_success(latency)
            return status, body, headers, False
        # typed upstream error: serve()'s JSON drives the retry decision
        body = body if isinstance(body, dict) else {}
        retriable = bool(body.get("retriable", status == 503))
        err_type = body.get("type", "")
        if err_type in ("EngineRestarted", "NonFiniteLogits") or status >= 500 and not body:
            # sick-replica signals feed the breaker; plain overload
            # (QueueFull, Draining) does not — a busy replica is healthy
            rep.record_failure(err_type or f"http {status}")
        else:
            rep.record_success(latency)
        return status, body, headers, retriable

    def _send_hedged(self, rep, payload, remaining_s, trace, attempt=0,
                     idem_key=None):
        """Dispatch with optional hedging: when the primary has not answered
        after `hedge_s`, duplicate the (zero-token, pure) request onto a
        second replica; the first complete response wins."""
        if self.hedge_s <= 0:
            return self._send(rep, payload, remaining_s, trace,
                              attempt=attempt, idem_key=idem_key)
        results = []
        results_mu = threading.Lock()
        first_done = threading.Event()

        def _run(r):
            out = self._send(r, payload, remaining_s, trace, attempt=attempt,
                             idem_key=idem_key)
            with results_mu:
                results.append((out, r))
            first_done.set()

        t1 = threading.Thread(target=_run, args=(rep,), daemon=True)
        t1.start()
        if not first_done.wait(self.hedge_s):
            alt = self.pick(
                exclude={rep.rid},
                adapter=payload.get("adapter") if isinstance(payload, dict) else None,
            )
            if alt is not None:
                _prof.record_router_event("hedges")
                t2 = threading.Thread(target=_run, args=(alt,), daemon=True)
                t2.start()
        first_done.wait()
        with results_mu:
            out, winner = results[0]
        if winner is not rep and out[0] == 200:
            _prof.record_router_event("hedge_wins")
        return out

    # -- rolling drain/restart ----------------------------------------------

    def rolling_restart(self, grace=None, ready_timeout=60.0, restart_fn=None):
        """Upgrade the fleet with zero dropped requests: one replica at a
        time, admin-drain -> wait for in-flight completion up to `grace` ->
        restart (launch Container SIGTERM -> grace -> SIGKILL -> respawn,
        or an injected `restart_fn(replica, grace)`) -> re-admit only after
        /healthz reports ready.  Returns a per-replica report."""
        if grace is None:
            grace = float(_core.flag("FLAGS_serve_drain_grace"))
        return [
            self._restart_one(rep, grace, ready_timeout, restart_fn)
            for rep in self.replicas
        ]

    def _restart_one(self, rep, grace, ready_timeout, restart_fn=None):
        rep.set_admin_draining(True)
        _prof.record_router_replica_state(rep.rid, "draining")
        drained = False
        try:
            deadline = time.monotonic() + grace
            while time.monotonic() < deadline:
                h = rep.probe(timeout=self.probe_timeout)
                if h is None or (
                    not h.get("active_slots") and not h.get("queue_depth")
                ):
                    drained = True
                    break
                time.sleep(0.05)
            fn = restart_fn
            if fn is None and rep.process is not None:
                fn = lambda r, g: r.process.restart(g)  # noqa: E731
            if fn is not None:
                _prof.record_router_replica_state(rep.rid, "restarting")
                fn(rep, grace)
            ready = False
            deadline = time.monotonic() + ready_timeout
            while time.monotonic() < deadline:
                h = rep.probe(timeout=self.probe_timeout)
                if h is not None and h.get("status") in ("ready", "live"):
                    ready = True
                    break
                time.sleep(0.05)
            return {
                "replica": rep.rid, "drained": drained,
                "restarted": fn is not None, "ready": ready,
            }
        finally:
            rep.set_admin_draining(False)
            _prof.record_router_replica_state(rep.rid, rep.state)

    # -- helpers -------------------------------------------------------------

    def _jitter_retry_after(self, ra):
        """±FLAGS_router_retry_after_jitter fractional jitter on shed
        Retry-After values: a takeover or brownout 503s many clients at
        once, and un-jittered identical waits resynchronize them into a
        thundering herd at the successor.  The float rides the body's
        `retry_after_s`; the header still floors at 1s."""
        if ra is None or self._retry_after_jitter <= 0:
            return ra
        with self._mu:
            u = self._rng.random()
        return max(0.0, ra * (1.0 + self._retry_after_jitter * (2.0 * u - 1.0)))

    @staticmethod
    def _clamp_retry_after(ra, deadline_t):
        """Never tell a client to retry after its own deadline."""
        if deadline_t is not None:
            ra = min(ra, max(0.0, deadline_t - time.monotonic()))
        return ra

    @staticmethod
    def _error(status, err_type, msg, retriable, retry_after=None,
               trace_id=None):
        headers = {}
        # `is not None`, not truthiness: a deadline-clamped retry_after of
        # 0.0 is a real "retry immediately" signal and must still emit the
        # header (rounded up to the 1s floor HTTP clients expect)
        if retry_after is not None:
            headers["Retry-After"] = str(max(1, int(retry_after + 0.5)))
        if trace_id:
            headers[_obs.HDR_TRACE] = trace_id
        return status, {
            "error": msg,
            "type": err_type,
            "retriable": bool(retriable),
            "retry_after_s": retry_after or 0,
            "trace_id": trace_id,
        }, headers


class RouterStandby:
    """Warm standby for the front door (the ISSUE 17 takeover state
    machine): WATCHING — the primary's rank-0 heartbeat seq advances;
    seq stalls for `FLAGS_router_takeover_timeout` on the STANDBY'S OWN
    clock (the launch controller's stale-counter scheme — no cross-process
    clock comparison) -> TAKING_OVER — replay the journal (repairing a
    torn final segment), rebuild replica handles from the journaled
    registry, restore breakers/drains/idempotency, synchronous probe
    sweep -> SERVING — the successor Router answers traffic and beats the
    same heartbeat slot.

    Thread-safe: `primary_alive()` may be polled concurrently with the
    optional `watch()` thread; every mutable field lives under `self._mu`.
    """

    def __init__(self, journal_root, heartbeat_root, replicas=(), *,
                 timeout=None, poll_interval=0.05, make_router=None,
                 router_kwargs=None):
        self.journal_root = str(journal_root)
        self.heartbeat_root = str(heartbeat_root)
        self.timeout = float(
            timeout if timeout is not None
            else _core.flag("FLAGS_router_takeover_timeout"))
        self.poll_interval = float(poll_interval)
        self.replicas = list(replicas)
        self.router_kwargs = dict(router_kwargs or {})
        self._make_router = make_router
        self._mu = threading.Lock()
        self._last_seq = None
        self._last_advance = None
        self._router = None
        self._watch_thread = None
        self._stop = threading.Event()

    @property
    def router(self):
        """The successor Router once takeover happened (else None)."""
        with self._mu:
            return self._router

    def primary_alive(self, now=None):
        """True while the primary's heartbeat seq keeps advancing, judged
        on THIS process's monotonic clock.  The first observation arms the
        staleness timer — a standby booted next to an already-dead primary
        still waits one full timeout before declaring death."""
        from ..fault import heartbeat as _hb

        now = time.monotonic() if now is None else now
        hb = _hb.scan_heartbeats(self.heartbeat_root).get(0)
        seq = hb.get("seq") if isinstance(hb, dict) else None
        with self._mu:
            if self._last_advance is None:
                self._last_advance = now
                self._last_seq = seq
                return True
            if seq is not None and seq != self._last_seq:
                self._last_seq = seq
                self._last_advance = now
                return True
            return (now - self._last_advance) < self.timeout

    def wait_for_death(self, timeout=60.0):
        """Poll until the primary is declared dead; False on timeout or
        stop()."""
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            if not self.primary_alive():
                return True
            if self._stop.wait(self.poll_interval):
                return False
        return False

    def takeover(self):
        """Become the front door: open the journal (replay + torn-tail
        repair happen inside `Journal`), build the successor Router —
        rehydration of registry/breakers/idempotency happens in its
        constructor — and probe the fleet synchronously before any
        traffic.  Returns the serving successor."""
        journal = Journal(self.journal_root)
        if self._make_router is not None:
            router = self._make_router(journal)
        else:
            router = Router(
                list(self.replicas), journal=journal,
                heartbeat=self.heartbeat_root, **self.router_kwargs,
            )
        router.start()
        with self._mu:
            self._router = router
        return router

    def watch(self, on_takeover=None):
        """Background supervision: poll the primary's heartbeat; on death,
        take over and hand the successor to `on_takeover(router)`."""
        with self._mu:
            if self._watch_thread is not None:
                return self

        def _run():
            while not self._stop.is_set():
                if not self.primary_alive():
                    router = self.takeover()
                    if on_takeover is not None:
                        on_takeover(router)
                    return
                if self._stop.wait(self.poll_interval):
                    return

        t = threading.Thread(target=_run, name="router-standby", daemon=True)
        with self._mu:
            self._watch_thread = t
        t.start()
        return self

    def stop(self):
        self._stop.set()
        with self._mu:
            t = self._watch_thread
        if t is not None:
            t.join(5)


def serve_router(replicas, port=8900, host="127.0.0.1", block=True, probe=True):
    """HTTP front door over a Router (mirrors inference.serve()'s shape):

    - GET  /health   -> 200
    - GET  /healthz  -> fleet snapshot (200 when >= 1 replica is ready)
    - GET  /metrics  -> Prometheus text exposition (role="router" label)
    - GET  /trace/<id> -> the router-side span tree for one trace id
    - POST /generate -> routed with failover + deadline propagation; the
      client's deadline arrives as `X-Deadline-Ms` (or body `deadline_s`),
      and each upstream hop receives only the remaining budget.  Trace
      context (`X-Trace-Id`/`X-Parent-Span`) is joined or minted and
      forwarded to the chosen replica; responses carry `X-Trace-Id`.

    Returns the ThreadingHTTPServer with `.router` attached; non-blocking
    callers get a daemon thread running `serve_forever()`.
    """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    router = replicas if isinstance(replicas, Router) else Router(replicas)
    if probe:
        router.start()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _reply(self, code, payload, headers=None):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/health":
                self._reply(200, {"status": "ok"})
            elif self.path == "/healthz":
                h = router.healthz()
                self._reply(200 if h["status"] == "ready" else 503, h)
            elif self.path == "/metrics":
                # bound address, not the port argument (0 = ephemeral)
                bh, bp = self.server.server_address[:2]
                body = _obs_metrics.render(
                    labels={"replica": f"{bh}:{bp}", "role": "router"}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", _obs_metrics.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith("/trace/"):
                tid = self.path[len("/trace/"):]
                roots = _obs.tree(tid)
                if roots:
                    self._reply(200, {"trace_id": tid, "spans": roots})
                else:
                    self._reply(404, {"error": f"no spans buffered for trace {tid!r}"})
            else:
                self._reply(404, {"error": "use POST /generate"})

        def do_POST(self):
            if self.path != "/generate":
                self._reply(404, {"error": "use POST /generate"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n))
            except Exception as e:
                self._reply(400, {"error": f"{type(e).__name__}: {e}"})
                return
            hdr = self.headers.get("X-Deadline-Ms")
            deadline_ms = float(hdr) if hdr is not None else None
            if deadline_ms is None and payload.get("deadline_s") is not None:
                deadline_ms = float(payload["deadline_s"]) * 1e3
            # the router owns the deadline now: strip the absolute field so
            # replicas see only the remaining budget via X-Deadline-Ms
            payload.pop("deadline_s", None)
            try:
                status, body, headers = router.handle_generate(
                    payload, deadline_ms=deadline_ms,
                    idem_key=self.headers.get("X-Idempotency-Key"),
                    trace=_obs.ctx_from_headers(self.headers),
                )
            except RouterCrashed:
                # the front door is dead: drop the connection with no
                # response bytes (the client sees a reset and resubmits
                # its idempotency key against the successor)
                self.close_connection = True
                return
            self._reply(status, body, headers={
                k: v for k, v in headers.items()
                if k.lower() in ("retry-after", "x-trace-id",
                                 "x-idempotency-replay")
            })

    server = ThreadingHTTPServer((host, port), Handler)
    server.router = router

    def _shutdown():
        router.stop()
        server.shutdown()

    server.stop_router = _shutdown
    if block:
        try:
            server.serve_forever()
        finally:
            router.stop()
        return server
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server
