"""Long-soak workload generator (ISSUE 16): the traffic that falsifies.

The north-star claim — "heavy traffic from millions of users" — needs a
workload that looks like one: a `Workload` describes a non-homogeneous
Poisson arrival process (diurnal modulation x a step function of burst
multipliers on a base rate) mixed with ADVERSARIAL requests (spent
deadlines, unknown adapters, over-bucket prompts — each with a typed
expected outcome), and `run_soak` drives it through a live `Router` with
a bounded worker pool while arming chaos faults (`router.replica.kill`/
`hang`/`flap`, `serve.decode.nan`, `autoscale.spawn`) on a schedule
through the same `FLAGS_fault_inject` registry production uses.

Determinism: arrivals, lengths, and the adversarial mix are drawn from
one seeded `numpy` RandomState via thinning (draw at the peak rate,
accept with probability rate(t)/peak), so a soak is replayable — same
seed, same request sequence, same fault schedule.

Scale: arrivals are generated lazily and results are folded into O(1)
counters plus a bounded latency reservoir, so `requests=10**6` costs
memory proportional to the reservoir, not the request count.  Exactly-
once accounting is client-side and exact: every offered request must
come back with exactly one terminal status (the router's contract), and
`SoakReport.exactly_once` is the audit.
"""

from __future__ import annotations

import math
import threading
import time

import numpy as np

_LATENCY_RESERVOIR = 65536  # sampled latencies kept for percentiles

# adversarial kinds and the HTTP statuses that count as "the typed outcome
# we provoked" (anything else is an unexpected_outcome in the report)
_EXPECTED = {
    "ok": (200,),
    # budget spent before admission -> 504 family (router sheds or the
    # replica rejects; under brownout a 503 shed is also within contract)
    "over_deadline": (504, 503),
    # unregistered adapter -> terminal typed 4xx, never retried: 404
    # AdapterUnknown on a LoRA fleet, typed 400 on a fleet with no arena
    "unknown_adapter": (404, 400),
    # prompt >= engine max_len -> typed 400 (ValueError at submit); the
    # router does not retry non-retriable 4xx
    "over_bucket": (400,),
}


class Workload:
    """Declarative soak traffic.  All knobs are data so a soak config can
    be printed into a bench record or a flight dump verbatim.

    rate_hz          base Poisson arrival rate
    duration_s       soak length (arrival clock, not wall-bounded)
    diurnal_period_s sinusoidal modulation period (0 = flat)
    diurnal_amp      modulation amplitude in [0, 1): rate x (1 + a*sin)
    steps            ((t_s, multiplier), ...) step function on the base
                     rate; the LATEST step at or before t applies — this
                     is the "traffic step-function" the acceptance soak
                     drives (e.g. ((0, 1), (120, 4), (300, 1)))
    prompt_len       (lo, hi) inclusive bounds for normal prompts
    max_new_tokens   per-request generation budget
    deadline_s       per-request deadline for NORMAL traffic (None = none)
    frac_*           adversarial mix fractions (summing under 1.0)
    over_bucket_len  prompt length for the over-bucket kind (default
                     max_len_hint + 8, i.e. reliably past the engine cap)
    adapters         known adapter names cycled onto normal traffic
    requests         optional hard cap on offered requests (None = until
                     duration_s of arrival time)
    """

    def __init__(self, *, rate_hz=20.0, duration_s=10.0, seed=0,
                 diurnal_period_s=0.0, diurnal_amp=0.0, steps=(),
                 prompt_len=(4, 12), max_new_tokens=4, deadline_s=None,
                 temperature=0.0, frac_over_deadline=0.0,
                 frac_unknown_adapter=0.0, frac_over_bucket=0.0,
                 over_bucket_len=None, max_len_hint=64, adapters=(),
                 vocab=256, requests=None):
        if not 0.0 <= diurnal_amp < 1.0:
            raise ValueError("diurnal_amp must be in [0, 1)")
        fr = frac_over_deadline + frac_unknown_adapter + frac_over_bucket
        if fr >= 1.0:
            raise ValueError("adversarial fractions must sum under 1.0")
        self.rate_hz = float(rate_hz)
        self.duration_s = float(duration_s)
        self.seed = int(seed)
        self.diurnal_period_s = float(diurnal_period_s)
        self.diurnal_amp = float(diurnal_amp)
        self.steps = tuple((float(t), float(m)) for t, m in steps)
        if any(m <= 0 for _, m in self.steps):
            raise ValueError("step multipliers must be > 0")
        self.prompt_len = (int(prompt_len[0]), int(prompt_len[1]))
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_s = deadline_s
        self.temperature = float(temperature)
        self.frac_over_deadline = float(frac_over_deadline)
        self.frac_unknown_adapter = float(frac_unknown_adapter)
        self.frac_over_bucket = float(frac_over_bucket)
        self.over_bucket_len = int(
            over_bucket_len if over_bucket_len is not None
            else max_len_hint + 8
        )
        self.adapters = tuple(adapters)
        self.vocab = int(vocab)
        self.requests = None if requests is None else int(requests)

    # -- the rate function ---------------------------------------------------

    def rate_at(self, t):
        """Instantaneous arrival rate at soak time t (Hz)."""
        r = self.rate_hz
        if self.diurnal_period_s > 0 and self.diurnal_amp > 0:
            r *= 1.0 + self.diurnal_amp * math.sin(
                2.0 * math.pi * t / self.diurnal_period_s
            )
        r *= self._step_mult(t)
        return max(0.0, r)

    def _step_mult(self, t):
        m = 1.0
        for ts, mult in self.steps:
            if t >= ts:
                m = mult
        return m

    def peak_rate(self):
        peak_step = max((m for _, m in self.steps), default=1.0)
        return self.rate_hz * (1.0 + self.diurnal_amp) * max(1.0, peak_step)

    # -- arrivals ------------------------------------------------------------

    def arrivals(self):
        """Lazy deterministic arrival stream: yields (t, kind, request)
        with t strictly increasing.  `request` is {"payload", "deadline_ms"}
        ready for `Router.handle_generate`.  Thinning keeps the draw count
        proportional to the PEAK rate while matching rate_at(t) exactly in
        distribution."""
        rng = np.random.RandomState(self.seed)
        peak = self.peak_rate()
        if peak <= 0:
            return
        t = 0.0
        n = 0
        while True:
            t += float(rng.exponential(1.0 / peak))
            if t >= self.duration_s:
                return
            if float(rng.uniform()) * peak > self.rate_at(t):
                continue  # thinned: the instantaneous rate is below peak
            yield t, *self._draw_request(rng, n)
            n += 1
            if self.requests is not None and n >= self.requests:
                return

    def _draw_request(self, rng, n):
        u = float(rng.uniform())
        lo, hi = self.prompt_len
        ids = rng.randint(1, self.vocab, size=int(rng.randint(lo, hi + 1)))
        payload = {
            "input_ids": ids.tolist(),
            "max_new_tokens": self.max_new_tokens,
            "temperature": self.temperature,
        }
        deadline_ms = (
            None if self.deadline_s is None else self.deadline_s * 1e3
        )
        if u < self.frac_over_deadline:
            kind = "over_deadline"
            deadline_ms = 0.001  # spent on arrival: sheds before admission
        elif u < self.frac_over_deadline + self.frac_unknown_adapter:
            kind = "unknown_adapter"
            payload["adapter"] = f"no-such-adapter-{n}"
        elif u < (self.frac_over_deadline + self.frac_unknown_adapter
                  + self.frac_over_bucket):
            kind = "over_bucket"
            payload["input_ids"] = rng.randint(
                1, self.vocab, size=self.over_bucket_len
            ).tolist()
        else:
            kind = "ok"
            if self.adapters:
                payload["adapter"] = self.adapters[n % len(self.adapters)]
        return kind, {"payload": payload, "deadline_ms": deadline_ms}


class SoakReport:
    """Exactly-once accounting + SLO summary for one soak run.  Counters
    are exact; latencies are a bounded reservoir (percentiles only)."""

    def __init__(self):
        self.offered = 0
        self.resolved = 0
        self.status_counts = {}  # http status -> n
        self.kind_counts = {}  # kind -> {"n", "expected", "unexpected"}
        self.error_types = {}  # typed error name -> n
        self.deadline_misses = 0  # ok-kind requests that 504'd
        self.ok_kind_total = 0
        self.latencies = []  # bounded reservoir, seconds
        self._res_rng = np.random.RandomState(20160816)
        self.wall_s = 0.0
        self.faults_armed = []

    def note(self, kind, status, body, latency_s):
        self.resolved += 1
        self.status_counts[status] = self.status_counts.get(status, 0) + 1
        k = self.kind_counts.setdefault(
            kind, {"n": 0, "expected": 0, "unexpected": 0}
        )
        k["n"] += 1
        expected = status in _EXPECTED.get(kind, (200,))
        k["expected" if expected else "unexpected"] += 1
        if status != 200 and isinstance(body, dict) and body.get("type"):
            t = body["type"]
            self.error_types[t] = self.error_types.get(t, 0) + 1
        if kind == "ok":
            self.ok_kind_total += 1
            if status == 504:
                self.deadline_misses += 1
        if len(self.latencies) < _LATENCY_RESERVOIR:
            self.latencies.append(latency_s)
        else:  # reservoir sampling keeps the percentile estimate unbiased
            j = int(self._res_rng.randint(0, self.resolved))
            if j < _LATENCY_RESERVOIR:
                self.latencies[j] = latency_s

    @property
    def exactly_once(self):
        """Every offered request came back with exactly one terminal
        status.  Workers record one outcome per dequeued request and the
        pool joins before the report closes, so offered == resolved IS
        the exactly-once audit at the client boundary."""
        return self.offered == self.resolved

    @property
    def miss_rate(self):
        """Deadline misses over ORGANIC traffic only (adversarial kinds
        provoke their failures on purpose and must not pollute the SLO)."""
        return (
            self.deadline_misses / self.ok_kind_total
            if self.ok_kind_total else 0.0
        )

    def _pctl(self, q):
        if not self.latencies:
            return 0.0
        v = sorted(self.latencies)
        return v[min(len(v) - 1, int(round(q * (len(v) - 1))))]

    def summary(self):
        ok = self.status_counts.get(200, 0)
        return {
            "offered": self.offered,
            "resolved": self.resolved,
            "exactly_once": self.exactly_once,
            "ok": ok,
            "status_counts": dict(self.status_counts),
            "kind_counts": {k: dict(v) for k, v in self.kind_counts.items()},
            "error_types": dict(self.error_types),
            "deadline_misses": self.deadline_misses,
            "miss_rate": round(self.miss_rate, 5),
            "latency_p50_ms": round(self._pctl(0.50) * 1e3, 2),
            "latency_p95_ms": round(self._pctl(0.95) * 1e3, 2),
            "wall_s": round(self.wall_s, 2),
            "requests_per_s": round(
                self.resolved / self.wall_s, 2) if self.wall_s else 0.0,
            "faults_armed": list(self.faults_armed),
        }


def run_soak(router, workload, *, threads=8, faults=(), realtime=True,
             queue_bound=4096, on_progress=None, crash_retries=100,
             crash_retry_s=0.05):
    """Drive `workload` through `router.handle_generate` with a bounded
    worker pool.  Returns a closed `SoakReport`.

    router     a live `Router`, or a zero-arg callable returning the
               CURRENT router (HA soaks pass a provider so workers pick
               up the standby's successor after a `router.crash` drill)
    faults     ((t_s, spec), ...): each `spec` is armed through
               `fault.injection.arm` when the arrival clock first passes
               t_s — the SAME registry and grammar production uses, so a
               soak's chaos schedule is one printable tuple
    realtime   True paces arrivals on the wall clock (latency numbers are
               meaningful); False dispatches as fast as the pool drains
               (throughput / million-request capability runs)
    on_progress  optional callable(report, t) invoked about once per
               arrival-clock second (progress logging in long soaks)
    crash_retries / crash_retry_s  resubmit budget when the front door
               dies mid-request (`RouterCrashed`): the worker re-attaches
               the SAME idempotency key and resubmits against whatever
               the provider returns, so a takeover window never breaks
               the exactly-once audit

    Every request carries a deterministic idempotency key
    (``soak-<seed>-<n>``), so a resubmit after a router crash joins or
    replays the original generation instead of double-generating.
    """
    import queue as _q

    from ..fault import injection as _finj
    from .router import RouterCrashed

    get_router = router if callable(router) else (lambda: router)
    report = SoakReport()
    work = _q.Queue(maxsize=queue_bound)
    done = threading.Event()
    mu = threading.Lock()

    def _worker():
        while True:
            item = work.get()
            if item is None:
                return
            kind, req = item
            key = req["payload"].get("idempotency_key")
            t0 = time.monotonic()
            try:
                for attempt in range(int(crash_retries) + 1):
                    try:
                        status, body, _hdrs = get_router().handle_generate(
                            req["payload"], deadline_ms=req["deadline_ms"]
                        )
                        break
                    except RouterCrashed:
                        # The front door died with zero response bytes on
                        # the wire; resubmitting the SAME key against the
                        # successor is the ISSUE 17 exactly-once drill.
                        # handle_generate pops the key, so re-attach it.
                        if attempt >= crash_retries:
                            raise
                        if key is not None:
                            req["payload"]["idempotency_key"] = key
                        time.sleep(crash_retry_s)
            except Exception as e:  # a raising router is a broken contract:
                status, body = -1, {"type": type(e).__name__}  # count it loud
            with mu:
                report.note(kind, status, body, time.monotonic() - t0)

    pool = [
        threading.Thread(target=_worker, name=f"soak-{i}", daemon=True)
        for i in range(int(threads))
    ]
    for t in pool:
        t.start()

    fault_sched = sorted(((float(ts), spec) for ts, spec in faults))
    fi = 0
    wall0 = time.monotonic()
    last_progress = 0.0
    try:
        for t_arr, kind, req in workload.arrivals():
            # Deterministic per-request idempotency key: replayable from
            # the seed, unique per offered request, honoured by the
            # router's dedupe cache (a crash-window resubmit reuses it).
            req["payload"].setdefault(
                "idempotency_key", f"soak-{workload.seed}-{report.offered}"
            )
            while fi < len(fault_sched) and fault_sched[fi][0] <= t_arr:
                spec = fault_sched[fi][1]
                _finj.arm(spec)
                report.faults_armed.append({"t": fault_sched[fi][0],
                                            "spec": spec})
                fi += 1
            if realtime:
                lag = wall0 + t_arr - time.monotonic()
                if lag > 0:
                    time.sleep(lag)
            report.offered += 1
            work.put((kind, req))
            if on_progress is not None and t_arr - last_progress >= 1.0:
                last_progress = t_arr
                with mu:
                    on_progress(report, t_arr)
    finally:
        for _ in pool:
            work.put(None)
        for t in pool:
            t.join()
        done.set()
        report.wall_s = time.monotonic() - wall0
    return report
