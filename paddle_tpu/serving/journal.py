"""Durable control-plane journal + idempotency cache (ISSUE 17).

Every layer below the front door survives kill -9 — engines warm-restart,
replicas fail over, the autoscaler rides drains — but the Router's
breaker states, replica registry, autoscaler clocks, and in-flight
accounting lived only in process memory.  This module makes that control
plane durable with the SAME discipline the checkpoint manifests use:
append-only checksummed records, atomic-rename segment files, and a
replay that folds records into state a successor can trust.

Journal format (one record per line, within numbered segment files):

    <compact-json>|<crc32-of-json-as-8-hex>\n

Segment files are named ``journal-<first_seq>.seg``; a new segment opens
every ``FLAGS_router_journal_segment_records`` appends and on every
process life (the previous life's tail may be torn).  ``replay`` folds
all segments oldest-first; a torn or checksum-failing record in the
FINAL segment truncates it there (counted, then repaired in place via
write-tmp + ``os.replace``, so the invariant "every non-final segment is
fully valid" holds across lives), while corruption in an earlier segment
raises :class:`JournalCorruption` — silently skipping interior history
would rehydrate a lying control plane.

``compact()`` folds the whole journal into one ``snapshot`` record
written to a fresh segment (tmp + atomic rename, then older segments are
deleted), pruning idempotency entries past their TTL.  Replayed state is
bit-for-bit identical before and after compaction — the fold function is
the single source of truth for both paths.

Record kinds folded into state (unknown kinds are ignored — forward
compatible):

    breaker     {rid, state, fails, open_remaining_s at write wall time}
    replica     {op: register|deregister|drain, rid, url, draining}
    autoscale   {band, last_action_wall, up_streak, down_streak}
    idem_admit  {key, rid}        an admitted in-flight idempotency key
    idem_done   {key, status, body}  a cached completed response
    idem_drop   {key}             a retriable outcome: never cached
    takeover    {}                a successor replayed this journal
    snapshot    {state}           a compaction checkpoint (replaces state)

The :class:`IdempotencyCache` is the other half of the crash-proof front
door: a TTL'd completed-response cache plus an in-flight join, used by
BOTH the router and ``inference.serve()`` — a client retry after a
connection reset (or a router death) can never produce two generations.
Stdlib-only: the standby/supervisor process must be able to replay a
journal without dragging in the model stack.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib

from .. import profiler as _prof
from ..framework import core as _core
from ..obs import flight as _flight


class JournalCorruption(RuntimeError):
    """A checksum failure in a NON-final segment: interior history is
    gone and a replay cannot be trusted.  (A torn final record is the
    normal crash signature and is recovered, not raised.)"""


# -- the fold: one source of truth for replay AND compaction ---------------


def empty_state():
    return {
        "seq": 0,
        "takeovers": 0,
        "breakers": {},        # rid -> {breaker, fails, open_until_wall}
        "replicas": {},        # rid -> {url, draining} (registration order)
        "autoscale": None,     # band + cooldown clocks, or None
        "idem": {},            # key -> {t, status, body} completed entries
        "idem_inflight": {},   # key -> {t, rid} admitted, never completed
    }


def fold(state, rec):
    """Fold one journal record into `state` (mutates and returns it).
    Pure w.r.t. everything but `state`; unknown kinds are ignored."""
    kind = rec.get("kind")
    state["seq"] = max(state["seq"], int(rec.get("seq", 0)))
    if kind == "breaker":
        state["breakers"][rec["rid"]] = {
            "breaker": rec["state"],
            "fails": int(rec.get("fails", 0)),
            "open_until_wall": float(rec.get("open_until_wall", 0.0)),
        }
    elif kind == "replica":
        op = rec.get("op")
        if op == "register":
            state["replicas"].setdefault(
                rec["rid"], {"url": rec.get("url", ""), "draining": False}
            )
        elif op == "deregister":
            state["replicas"].pop(rec["rid"], None)
            state["breakers"].pop(rec["rid"], None)
        elif op == "drain" and rec["rid"] in state["replicas"]:
            state["replicas"][rec["rid"]]["draining"] = bool(rec["draining"])
    elif kind == "autoscale":
        state["autoscale"] = {
            "band": list(rec.get("band", ())),
            "last_action_wall": float(rec.get("last_action_wall", 0.0)),
            "up_streak": int(rec.get("up_streak", 0)),
            "down_streak": int(rec.get("down_streak", 0)),
        }
    elif kind == "idem_admit":
        state["idem_inflight"][rec["key"]] = {
            "t": float(rec.get("t", 0.0)), "rid": rec.get("rid"),
        }
    elif kind == "idem_done":
        state["idem_inflight"].pop(rec["key"], None)
        state["idem"][rec["key"]] = {
            "t": float(rec.get("t", 0.0)),
            "status": int(rec["status"]),
            "body": rec.get("body"),
        }
    elif kind == "idem_drop":
        state["idem_inflight"].pop(rec["key"], None)
        state["idem"].pop(rec["key"], None)
    elif kind == "takeover":
        state["takeovers"] += 1
    elif kind == "snapshot":
        seq = state["seq"]
        state.clear()
        state.update(rec["state"])
        state["seq"] = max(state["seq"], seq)
    return state


# -- segment encoding ------------------------------------------------------


def _encode(rec):
    payload = json.dumps(rec, separators=(",", ":"), sort_keys=True)
    crc = zlib.crc32(payload.encode()) & 0xFFFFFFFF
    return f"{payload}|{crc:08x}\n"


def _decode(line):
    """Parse one journal line; None when torn or checksum-failing."""
    line = line.rstrip("\n")
    payload, sep, crc = line.rpartition("|")
    if not sep or len(crc) != 8:
        return None
    try:
        if int(crc, 16) != (zlib.crc32(payload.encode()) & 0xFFFFFFFF):
            return None
        rec = json.loads(payload)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


def _segment_name(first_seq):
    return f"journal-{int(first_seq):012d}.seg"


def _list_segments(root):
    try:
        names = os.listdir(root)
    except OSError:
        return []
    segs = []
    for name in names:
        if name.startswith("journal-") and name.endswith(".seg"):
            try:
                segs.append((int(name[len("journal-"):-len(".seg")]), name))
            except ValueError:
                continue
    return [name for _, name in sorted(segs)]


def replay(root):
    """Fold every segment under `root` oldest-first.

    Returns ``(state, stats)`` where stats carries ``records`` applied and
    ``torn`` (bad records dropped from the final segment's tail).  A bad
    record in a NON-final segment — or mid-segment garbage followed by
    more valid lines in the final one — raises :class:`JournalCorruption`:
    only a torn TAIL is the honest crash signature."""
    state = empty_state()
    stats = {"records": 0, "torn": 0}
    segs = _list_segments(root)
    for si, name in enumerate(segs):
        final = si == len(segs) - 1
        with open(os.path.join(root, name)) as f:
            lines = f.readlines()
        bad_at = None
        for li, line in enumerate(lines):
            rec = _decode(line)
            if rec is None:
                bad_at = li
                break
            fold(state, rec)
            stats["records"] += 1
        if bad_at is not None:
            if not final:
                raise JournalCorruption(
                    f"corrupt record {bad_at} in non-final segment {name}"
                )
            stats["torn"] += len(lines) - bad_at
    return state, stats


class Journal:
    """Append-only, checksummed, compacting control-plane journal.

    Opening an existing directory replays it (repairing a torn final
    tail in place) and continues appending into a FRESH segment; the
    folded state is kept incrementally current so ``compact()`` and
    rehydration never re-read disk.  Thread-safe: appends come from
    handler threads, the probe thread, the breaker paths, and the
    autoscaler control loop — every mutable field lives under one
    ``self._mu``."""

    def __init__(self, root, segment_records=None, ttl_s=None, fsync=False):
        self.root = str(root)
        self.segment_records = int(
            segment_records if segment_records is not None
            else _core.flag("FLAGS_router_journal_segment_records")
        )
        self.ttl_s = float(
            ttl_s if ttl_s is not None else _core.flag("FLAGS_router_idem_ttl")
        )
        self.fsync = bool(fsync)
        os.makedirs(self.root, exist_ok=True)
        self._mu = threading.Lock()
        state, stats = replay(self.root)
        if stats["torn"]:
            self._repair_tail()
            _prof.record_router_event("journal_torn_records", stats["torn"])
            _flight.record(
                "journal", f"torn tail repaired: {stats['torn']} record(s) "
                "dropped", root=self.root, seq=state["seq"],
            )
        with self._mu:
            self._state = state
            self._seq = int(state["seq"])
            self._resumed = stats["records"] > 0
            self._active = None         # open file handle of the segment
            self._active_records = 0
            self._compactions = 0
            self._torn = stats["torn"]

    # -- introspection -------------------------------------------------------

    @property
    def seq(self):
        with self._mu:
            return self._seq

    @property
    def resumed(self):
        """True when opening found prior records — a successor's signature
        (a fresh journal directory starts empty)."""
        with self._mu:
            return self._resumed

    def state_snapshot(self):
        """Deep copy of the folded state (rehydration reads this once)."""
        with self._mu:
            return json.loads(json.dumps(self._state))

    def stats(self):
        with self._mu:
            return {
                "seq": self._seq,
                "segments": len(_list_segments(self.root)),
                "compactions": self._compactions,
                "torn_records": self._torn,
            }

    # -- appending -----------------------------------------------------------

    def append(self, kind, **fields):
        """Write one record (checksummed, flushed) and fold it into the
        live state.  Returns the record's seq."""
        with self._mu:
            self._seq += 1
            rec = {"seq": self._seq, "kind": str(kind), "t": time.time()}
            rec.update(fields)
            self._write_locked(rec)
            fold(self._state, rec)
            seq = self._seq
        _prof.record_router_event("journal_appends")
        return seq

    def _write_locked(self, rec):
        if self._active is None or self._active_records >= self.segment_records:
            if self._active is not None:
                self._active.close()
            path = os.path.join(self.root, _segment_name(rec["seq"]))
            self._active = open(path, "a")
            self._active_records = 0
        self._active.write(_encode(rec))
        self._active.flush()
        if self.fsync:
            os.fsync(self._active.fileno())
        self._active_records += 1

    # -- compaction ----------------------------------------------------------

    def compact(self, now=None):
        """Fold the whole journal into ONE snapshot record in a fresh
        segment (write-tmp + atomic rename — the checkpoint-manifest
        discipline), then delete the older segments.  Expired idempotency
        entries are pruned on the way through.  Returns the snapshot's
        seq."""
        now = time.time() if now is None else now
        with self._mu:
            if self._active is not None:
                self._active.close()
                self._active = None
                self._active_records = 0
            self._prune_idem_locked(now)
            old = _list_segments(self.root)
            self._seq += 1
            rec = {
                "seq": self._seq, "kind": "snapshot", "t": now,
                "state": json.loads(json.dumps(self._state)),
            }
            rec["state"]["seq"] = self._seq
            path = os.path.join(self.root, _segment_name(self._seq))
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(_encode(rec))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            for name in old:
                if name != _segment_name(self._seq):
                    try:
                        os.remove(os.path.join(self.root, name))
                    except OSError:
                        pass
            fold(self._state, rec)
            self._compactions += 1
            seq = self._seq
        _prof.record_router_event("journal_compactions")
        _flight.record("journal", "compacted", seq=seq, dropped_segments=len(old))
        return seq

    def _prune_idem_locked(self, now):
        idem = self._state["idem"]
        for key in [k for k, v in idem.items() if now - v["t"] > self.ttl_s]:
            del idem[key]

    def _repair_tail(self):
        """Rewrite the final segment with only its valid prefix (tmp +
        atomic rename), so after THIS life appends new segments the torn
        one is no longer final yet still replays clean."""
        segs = _list_segments(self.root)
        if not segs:
            return
        path = os.path.join(self.root, segs[-1])
        with open(path) as f:
            lines = f.readlines()
        good = []
        for line in lines:
            if _decode(line) is None:
                break
            good.append(line)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.writelines(good)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def close(self):
        with self._mu:
            if self._active is not None:
                self._active.close()
                self._active = None


class IdempotencyCache:
    """TTL'd completed-response cache + in-flight join, keyed by the
    client's idempotency key.

    ``begin(key)`` returns one of three verdicts:

      ("new", None)     — first sight: the caller executes the request and
                          MUST finish with ``complete``/``abandon``
      ("join", entry)   — the key is live right now: ``wait(entry)``
                          blocks until the live request completes and
                          returns its exact response (one generation,
                          byte-identical answers)
      ("done", resp)    — a completed response inside the TTL: replay it

    Only terminal outcomes are retained: 200s and non-retriable typed
    errors.  A retriable error (503 shed, restart) wakes joiners with the
    response but drops the entry, so a later retry re-executes — caching
    a shed would turn one brownout into a permanent failure.  All state
    lives under one ``self._mu``; entries are only ever mutated there."""

    class _Entry:
        __slots__ = ("event", "response", "done", "t_done", "rid")

        def __init__(self):
            self.event = threading.Event()
            self.response = None  # (status, body, headers)
            self.done = False
            self.t_done = 0.0
            self.rid = None

    def __init__(self, ttl_s=None, journal=None):
        self.ttl_s = float(
            ttl_s if ttl_s is not None else _core.flag("FLAGS_router_idem_ttl")
        )
        self.journal = journal
        self._mu = threading.Lock()
        self._entries = {}

    def begin(self, key, now=None):
        now = time.time() if now is None else now
        journal_admit = False
        with self._mu:
            self._purge_locked(now)
            entry = self._entries.get(key)
            if entry is None:
                entry = self._Entry()
                self._entries[key] = entry
                journal_admit = True
                out = ("new", None)
            elif not entry.done:
                out = ("join", entry)
            else:
                out = ("done", entry.response)
        if journal_admit and self.journal is not None:
            self.journal.append("idem_admit", key=key)
        if out[0] == "join":
            _prof.record_router_event("idem_joins")
        elif out[0] == "done":
            _prof.record_router_event("idem_hits")
        return out

    def wait(self, entry, timeout=600.0):
        """Block on a joined entry; returns its (status, body, headers)
        response, or None when the live request abandoned (crash) or the
        wait timed out — the caller retries or fails typed."""
        if not entry.event.wait(timeout):
            return None
        with self._mu:
            return entry.response

    def complete(self, key, status, body, headers=None, now=None):
        """Terminal outcome for a key: wake joiners with the exact
        response; retain it (and journal it) only when replaying it later
        is correct.  Returns True when the response was cached."""
        now = time.time() if now is None else now
        retain = status == 200 or (
            isinstance(body, dict) and body.get("retriable") is False
        )
        with self._mu:
            entry = self._entries.get(key)
            if entry is None:
                entry = self._Entry()
                self._entries[key] = entry
            entry.response = (status, body, dict(headers or {}))
            entry.done = True
            entry.t_done = now
            if not retain:
                self._entries.pop(key, None)
            entry.event.set()
        if self.journal is not None:
            if retain:
                self.journal.append("idem_done", key=key, status=int(status),
                                    body=body)
            else:
                self.journal.append("idem_drop", key=key)
        return retain

    def abandon(self, key):
        """The live request died without a terminal response (router
        crash, raised handler): drop the entry and wake joiners with no
        response, so they fail over with the client's retry contract
        intact."""
        with self._mu:
            entry = self._entries.pop(key, None)
            if entry is not None:
                entry.event.set()
        if entry is not None and self.journal is not None:
            self.journal.append("idem_drop", key=key)

    def restore(self, done_entries, now=None):
        """Load journaled completed responses (successor rehydration).
        Entries past the TTL are skipped; live entries never overwrite."""
        now = time.time() if now is None else now
        n = 0
        with self._mu:
            for key, v in done_entries.items():
                if now - v["t"] > self.ttl_s or key in self._entries:
                    continue
                entry = self._Entry()
                entry.response = (int(v["status"]), v["body"], {})
                entry.done = True
                entry.t_done = float(v["t"])
                entry.event.set()
                self._entries[key] = entry
                n += 1
        return n

    def stats(self):
        with self._mu:
            done = sum(1 for e in self._entries.values() if e.done)
            return {"cached": done, "inflight": len(self._entries) - done}

    def _purge_locked(self, now):
        dead = [
            k for k, e in self._entries.items()
            if e.done and now - e.t_done > self.ttl_s
        ]
        for k in dead:
            del self._entries[k]
