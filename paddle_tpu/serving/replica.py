"""Replica handle + managed replica worker for the serving router (ISSUE 9).

A `Replica` is the router's view of ONE `serve()` instance: its probe-driven
lifecycle state (ready/draining/dead/down), the load signals `/healthz`
exports (queue depth, drain estimate, page-pool free fraction, EWMA decode
step time), a per-replica circuit breaker (closed -> open on consecutive
failures -> half-open trial -> closed), and the transport used to dispatch
`/generate` with the remaining deadline budget in `X-Deadline-Ms`.

A `ReplicaProcess` is a router-MANAGED replica: a subprocess spawned through
the launch controller's `Container` (same env contract, `workerlog.N`
capture), which is what gives the router `kill9()` for chaos drills and
`restart(grace)` for rolling upgrades.  Running this module as a script
(`python paddle_tpu/serving/replica.py --port N`) starts one replica worker:
a deterministically seeded tiny model behind a warmed engine and `serve()` —
identical seeds across workers mean identical weights, so greedy outputs are
bit-identical whichever replica answers (the property failover relies on).
"""

# PEP 366 bootstrap: the launch Container execs this file as a plain script
# (`python -u .../replica.py`), where relative imports have no package; put
# the repo root on sys.path and claim the package before importing anything.
import os
import sys

if __package__ in (None, ""):  # pragma: no cover - subprocess entry only
    sys.path.insert(
        0,
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    )
    import paddle_tpu.serving  # noqa: F401  (run the package __init__)

    __package__ = "paddle_tpu.serving"

import json
import threading
import time
import urllib.error
import urllib.request

from .. import profiler as _prof
from ..framework import core as _core
from ..obs import flight as _flight
from ..obs import trace as _obs


class ReplicaTransportError(RuntimeError):
    """Transport-level failure talking to a replica: connect refused, reset,
    timeout.  `response_started` records whether any response bytes arrived
    before the failure — the router only retries when NOTHING reached it, so
    exactly-once delivery survives failover."""

    def __init__(self, msg, response_started=False):
        super().__init__(msg)
        self.response_started = bool(response_started)


class Replica:
    """Router-side handle for one serve() endpoint.

    State machine (probe-driven):
      connecting -> ready -> draining -> dead
                 \\-> down (probe failed) -> ready (probe recovered)
    plus a router-owned `admin_draining` bit for rolling restarts (the
    replica itself keeps serving; the router just stops picking it).

    All mutable fields are guarded by `self._mu`: the probe thread, handler
    threads, and the rolling-restart orchestrator all touch this object.
    """

    def __init__(self, rid, base_url, process=None,
                 breaker_threshold=None, breaker_cooldown=None):
        self.rid = str(rid)
        self.base_url = base_url.rstrip("/")
        self.process = process  # ReplicaProcess or None (external endpoint)
        self.breaker_threshold = int(
            breaker_threshold if breaker_threshold is not None
            else _core.flag("FLAGS_router_breaker_threshold")
        )
        self.breaker_cooldown = float(
            breaker_cooldown if breaker_cooldown is not None
            else _core.flag("FLAGS_router_breaker_cooldown")
        )
        self._mu = threading.Lock()
        self._state = "connecting"
        self._admin_draining = False
        self._breaker = "closed"
        self._fails = 0  # consecutive failures toward the breaker threshold
        self._open_until = 0.0
        self._trial_inflight = False  # the single half-open trial
        self._ewma_latency_s = None
        self._queue_depth = 0
        self._active_slots = 0
        self._drain_estimate_s = 0.0
        self._page_free_frac = 1.0
        self._decode_ewma_ms = 0.0
        self._tokens_per_step = 1.0
        self._deadline_miss_rate = 0.0
        self._lora_adapters = ()  # resident adapter names from healthz (ISSUE 12)
        # disaggregated serving (ISSUE 19): the role the replica booted in
        # (colocated/prefill/decode) and its decode-side reservation count,
        # both folded from /healthz — pick_pair() routes on these
        self._role = "colocated"
        self._reserved_pages = 0
        # long-context tier (ISSUE 20): context-parallel degree and resident
        # session count folded from /healthz — surfaced for observability
        # and the session drill assertions, not scored on
        self._cp = 1
        self._sessions_resident = 0
        self._probes_ok = 0
        self._probes_failed = 0
        # crash-proof front door (ISSUE 17): breaker transitions are
        # journaled so a successor router does not re-close onto a sick
        # replica; open_until is mirrored in wall time because monotonic
        # clocks do not survive process death
        self._journal = None
        self._open_until_wall = 0.0

    # -- snapshots -----------------------------------------------------------

    @property
    def state(self):
        with self._mu:
            return self._state

    @property
    def breaker(self):
        with self._mu:
            return self._breaker

    def snapshot(self):
        """Point-in-time copy of the routing-relevant state (lock held once;
        the router scores candidates off this, never off live fields)."""
        with self._mu:
            return {
                "id": self.rid,
                "url": self.base_url,
                "state": self._state,
                "admin_draining": self._admin_draining,
                "breaker": self._breaker,
                "consecutive_fails": self._fails,
                "ewma_latency_s": self._ewma_latency_s or 0.0,
                "queue_depth": self._queue_depth,
                "active_slots": self._active_slots,
                "drain_estimate_s": self._drain_estimate_s,
                "page_free_frac": self._page_free_frac,
                "decode_ewma_ms": self._decode_ewma_ms,
                "tokens_per_step": self._tokens_per_step,
                "deadline_miss_rate": self._deadline_miss_rate,
                "lora_adapters": self._lora_adapters,
                "role": self._role,
                "reserved_pages": self._reserved_pages,
                "cp": self._cp,
                "sessions_resident": self._sessions_resident,
                "probes_ok": self._probes_ok,
                "probes_failed": self._probes_failed,
            }

    def set_admin_draining(self, flag):
        with self._mu:
            self._admin_draining = bool(flag)
            journal = self._journal
        if journal is not None:
            journal.append("replica", op="drain", rid=self.rid,
                           draining=bool(flag))

    # -- durable control plane (ISSUE 17) ------------------------------------

    def bind_journal(self, journal):
        """Attach the control-plane journal: breaker transitions and drain
        decisions append to it from here on (appends happen OUTSIDE `_mu` —
        the journal has its own lock)."""
        with self._mu:
            self._journal = journal

    def restore_breaker(self, state, fails, open_until_wall, now=None):
        """Rehydrate breaker state from a journal replay.  The journaled
        open-until is wall clock; convert the REMAINING cooldown onto this
        process's monotonic clock (an expired cooldown restores as open
        with an immediate half-open trial — safe either way)."""
        now = time.time() if now is None else now
        remaining = max(0.0, float(open_until_wall) - now)
        with self._mu:
            if state == "open":
                self._breaker = "open"
                self._open_until = time.monotonic() + remaining
                self._open_until_wall = float(open_until_wall)
            else:
                self._breaker = "closed"
                self._open_until = 0.0
                self._open_until_wall = 0.0
            self._fails = int(fails)
            self._trial_inflight = False

    def _journal_breaker(self, journal, state, fails, open_until_wall):
        if journal is not None:
            journal.append("breaker", rid=self.rid, state=state,
                           fails=int(fails),
                           open_until_wall=float(open_until_wall))

    # -- circuit breaker -----------------------------------------------------

    def allow(self, now=None):
        """Breaker gate at dispatch time.  closed -> always; open -> only
        after the cooldown, transitioning to half_open; half_open -> exactly
        ONE trial request at a time (the caller reports the outcome through
        record_success / record_failure)."""
        now = time.monotonic() if now is None else now
        half_opened = False
        with self._mu:
            if self._breaker == "closed":
                ok = True
            elif self._breaker == "open":
                if now >= self._open_until:
                    self._breaker = "half_open"
                    self._trial_inflight = True
                    half_opened = True
                    ok = True
                else:
                    ok = False
            else:  # half_open: admit one trial
                if self._trial_inflight:
                    ok = False
                else:
                    self._trial_inflight = True
                    ok = True
        if half_opened:
            _prof.record_router_event("breaker_half_open")
            _flight.record("breaker", f"{self.rid} open -> half_open (trial)")
        return ok

    def record_success(self, latency_s=None):
        """A dispatched request completed (any well-formed response, 200 or
        typed error: the replica is alive and talking)."""
        closed = False
        with self._mu:
            self._fails = 0
            self._trial_inflight = False
            if self._breaker != "closed":
                self._breaker = "closed"
                self._open_until_wall = 0.0
                closed = True
            if latency_s is not None:
                self._ewma_latency_s = (
                    latency_s if self._ewma_latency_s is None
                    else 0.8 * self._ewma_latency_s + 0.2 * latency_s
                )
            journal = self._journal
        if closed:
            _prof.record_router_event("breaker_closes")
            _flight.record("breaker", f"{self.rid} -> closed")
            self._journal_breaker(journal, "closed", 0, 0.0)

    def record_failure(self, reason=""):
        """A sick-replica signal (transport failure, failed probe, engine
        restarted/dead): consecutive failures trip the breaker open; a
        failed half-open trial re-opens it for another cooldown."""
        tripped = False
        now = time.monotonic()
        with self._mu:
            self._fails += 1
            fails = self._fails
            self._trial_inflight = False
            if self._breaker == "half_open" or (
                self._breaker == "closed" and self._fails >= self.breaker_threshold
            ):
                self._breaker = "open"
                self._open_until = now + self.breaker_cooldown
                self._open_until_wall = time.time() + self.breaker_cooldown
                tripped = True
            open_until_wall = self._open_until_wall
            journal = self._journal
        if tripped:
            _prof.record_router_event("breaker_trips")
            _flight.record(
                "breaker", f"{self.rid} -> open: {reason}",
                fails=fails, cooldown_s=self.breaker_cooldown,
            )
            self._journal_breaker(journal, "open", fails, open_until_wall)

    # -- probing -------------------------------------------------------------

    def probe(self, timeout=None):
        """One /healthz probe: refresh lifecycle state + load gauges.
        Returns the healthz dict (possibly from a 503 body: draining/dead
        replicas still answer), or None on transport failure (state ->
        down, counts as a breaker failure)."""
        if timeout is None:
            timeout = float(_core.flag("FLAGS_router_probe_timeout"))
        try:
            with urllib.request.urlopen(
                self.base_url + "/healthz", timeout=timeout
            ) as resp:
                h = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                h = json.loads(e.read())
            except Exception:
                h = None
        except Exception:
            h = None
        if not isinstance(h, dict) or "status" not in h:
            with self._mu:
                self._state = "down"
                self._probes_failed += 1
            self.record_failure("probe failed")
            return None
        self._note_healthz(h)
        return h

    def _note_healthz(self, h):
        """Fold one healthz payload into the handle (also called by the
        router when a drain poll already fetched it)."""
        status = h.get("status")
        state = {
            "ready": "ready", "live": "ready",
            "draining": "draining", "dead": "dead",
        }.get(status, "down")
        with self._mu:
            self._state = state
            self._probes_ok += 1
            self._queue_depth = int(h.get("queue_depth", 0))
            self._active_slots = int(h.get("active_slots", 0))
            self._drain_estimate_s = float(h.get("drain_estimate_s", 0.0))
            self._page_free_frac = float(h.get("page_free_frac", 1.0))
            self._decode_ewma_ms = float(h.get("decode_ewma_ms", 0.0))
            self._tokens_per_step = float(h.get("tokens_per_step", 1.0))
            self._deadline_miss_rate = float(h.get("deadline_miss_rate", 0.0))
            self._role = str(h.get("role", "colocated"))
            self._reserved_pages = int(h.get("reserved_pages", 0))
            self._cp = int(h.get("cp", 1))
            sess = h.get("sessions")
            self._sessions_resident = (
                int(sess.get("sessions_resident", 0))
                if isinstance(sess, dict) else 0
            )
            lora = h.get("lora")
            if isinstance(lora, dict):
                self._lora_adapters = tuple(lora.get("adapters", ()))
        if state == "ready":
            self.record_success()
        elif state == "dead":
            self.record_failure("replica dead")

    def note_probe_failure(self, reason="injected"):
        """Probe-failure path without the HTTP round trip (the
        router.replica.flap fault injects here)."""
        with self._mu:
            self._state = "down"
            self._probes_failed += 1
        self.record_failure(reason)

    # -- transport -----------------------------------------------------------

    def post_generate(self, payload, remaining_s=None, timeout=None,
                      trace=None, idem_key=None):
        """One /generate dispatch.  Forwards the remaining deadline budget
        as X-Deadline-Ms (the hop contract serve() decodes back into
        `EngineRequest.deadline_s`) and the trace context as X-Trace-Id /
        X-Parent-Span (`trace` is the router's `(trace_id, forward_span_id)`
        pair).  Returns (status, body, headers, latency_s) for ANY complete
        HTTP response — typed upstream errors come back as their status +
        JSON, the router decides on `retriable`.  Raises
        ReplicaTransportError when the connection dies."""
        return self.post_json("/generate", payload, remaining_s=remaining_s,
                              timeout=timeout, trace=trace, idem_key=idem_key)

    def post_json(self, path, payload, remaining_s=None, timeout=None,
                  trace=None, idem_key=None):
        """One POST dispatch to `path` (the generalized transport behind
        post_generate; the disaggregated pipeline's /reserve and /prefill
        hops ride it with the same deadline/trace/exactly-once contract)."""
        from ..fault import injection as _inj

        # an armed router.replica.hang stands in for a wedged connection:
        # the dispatch blocks, bounded by the HTTP timeout below
        _inj.inject_hang("router.replica.hang", context=self.rid)
        data = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.base_url + path, data=data,
            headers={"Content-Type": "application/json"},
        )
        if remaining_s is not None:
            req.add_header("X-Deadline-Ms", str(int(remaining_s * 1e3)))
        if idem_key:
            # serve-side dedupe: replica replays its cached response when a
            # router retry (or a successor router) resubmits a key whose
            # generation already completed — exactly one generation per key
            req.add_header("X-Idempotency-Key", str(idem_key))
        if trace is not None:
            req.add_header(_obs.HDR_TRACE, trace[0])
            if trace[1]:
                req.add_header(_obs.HDR_PARENT, trace[1])
        if timeout is None:
            timeout = (remaining_s + 5.0) if remaining_s is not None else 600.0
        t0 = time.monotonic()
        started = False
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                started = True
                raw = resp.read()
                status, headers = resp.status, dict(resp.headers)
        except urllib.error.HTTPError as e:
            # a complete (typed) error response, not a transport failure
            raw = e.read()
            status, headers = e.code, dict(e.headers)
        except Exception as e:
            raise ReplicaTransportError(
                f"{type(e).__name__}: {e}", response_started=started
            ) from None
        try:
            body = json.loads(raw) if raw else {}
        except ValueError:
            body = {}
        return status, body, headers, time.monotonic() - t0


class ReplicaProcess:
    """A router-managed replica worker: this module run as a script through
    the launch controller's `Container` (same env contract + workerlog.N
    capture as a launched trainer).  Gives the router the process-level
    verbs the fleet story needs: `kill9()` for the chaos drill and
    `restart(grace)` — SIGTERM -> drain grace -> SIGKILL -> respawn — for
    rolling upgrades."""

    def __init__(self, index, port, log_dir, host="127.0.0.1", extra_args=()):
        from ..distributed.launch.main import Container

        self.port = int(port)
        self.host = host
        # rank index+1 keeps worker stdout in workerlog files (the launch
        # Container lets rank 0 inherit the parent console)
        self.container = Container(
            rank=int(index) + 1,
            world_size=1,
            endpoints=[],
            script=os.path.abspath(__file__),
            script_args=["--port", str(port), "--host", host, *extra_args],
            log_dir=log_dir,
        )

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def start(self):
        self.container.start()
        return self

    def alive(self):
        return self.container.proc is not None and self.container.poll() is None

    def kill9(self):
        self.container.kill9()

    def restart(self, grace=10.0):
        return self.container.restart(grace)

    def terminate(self):
        self.container.terminate()


def main(argv=None):
    """Replica worker entrypoint: deterministically seeded tiny model ->
    warmed continuous-batching engine -> serve() with SIGTERM drain."""
    import argparse

    p = argparse.ArgumentParser(prog="paddle_tpu.serving.replica")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--max-len", type=int, default=64)
    p.add_argument("--buckets", default="8,16")
    p.add_argument("--queue-depth", type=int, default=32)
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument(
        "--lora", default="",
        help="comma list of adapter specs name[:rank] to register and serve "
             "(forces the paged engine; weights are seeded by list position, "
             "so identical --lora strings mean identical adapters fleet-wide)",
    )
    p.add_argument(
        "--tp", type=int, default=1,
        help="tensor-parallel degree: shard the model, KV arena, and fused "
             "decode kernel over the first N devices of an 'mp' mesh (heads "
             "and kv_heads must divide by N; greedy outputs stay "
             "token-identical to --tp 1, so mixed-degree fleets still "
             "satisfy the failover contract)",
    )
    p.add_argument(
        "--role", default="colocated",
        choices=("colocated", "prefill", "decode"),
        help="disaggregated serving role (ISSUE 19): 'prefill' workers "
             "answer /prefill with exported page payloads, 'decode' workers "
             "import them via /generate handoffs (both force the paged "
             "engine; 'colocated' is the classic do-everything replica)",
    )
    p.add_argument(
        "--kv-quant", default="none", choices=("none", "int8"),
        help="KV-cache storage precision (forces the paged engine): 'int8' "
             "stores K/V pages as int8 with per-row float32 scales, roughly "
             "doubling the page pool the same HBM budget buys; the fused "
             "decode kernel dequantizes per page tile in VMEM",
    )
    args = p.parse_args(argv)

    import numpy as np

    # identical seed across workers -> identical weights -> greedy outputs
    # bit-identical whichever replica serves (the failover contract)
    np.random.seed(args.seed)
    from ..inference import serve
    from ..inference.engine import ContinuousBatchingEngine
    from ..models.llama import LlamaConfig, LlamaForCausalLM

    model = LlamaForCausalLM(
        LlamaConfig.tiny(tensor_parallel_degree=args.tp)
    )
    extra = {}
    if args.tp > 1:
        extra["tp"] = args.tp
    if args.lora:
        # same --lora string on every worker -> same registration order ->
        # same seeds -> bit-identical adapter weights (the failover contract
        # extends to LoRA outputs)
        from ..lora import AdapterArena, AdapterRegistry, make_random

        reg = AdapterRegistry(model.config)
        for i, spec in enumerate(args.lora.split(",")):
            name, _, rank = spec.partition(":")
            make_random(reg, name, rank=int(rank) if rank else 4, seed=i + 1)
        extra.update(paged=True, page_size=8, lora=AdapterArena(reg))
    if args.kv_quant != "none":
        # quantized arenas only exist on the paged engine; the flag opts
        # the replica into paging rather than erroring on the dense cache
        extra.update(paged=True, kv_quant=args.kv_quant)
        extra.setdefault("page_size", 8)
    if args.role != "colocated":
        # disaggregated roles are page-handoff roles by definition: the
        # wire format IS the page arena rows, so both ends must be paged
        extra.update(paged=True, role=args.role)
        extra.setdefault("page_size", 8)
    eng = ContinuousBatchingEngine(
        model,
        slots=args.slots,
        max_len=args.max_len,
        prefill_buckets=[int(b) for b in args.buckets.split(",")],
        queue_depth=args.queue_depth,
        seed=0,
        **extra,
    )
    eng.warmup()
    serve(eng, port=args.port, host=args.host, block=True, handle_signals=True)
    return 0


if __name__ == "__main__":  # pragma: no cover - subprocess entry only
    sys.exit(main())
