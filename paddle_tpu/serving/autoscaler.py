"""Closed-loop serving autoscaler (ISSUE 16): the controller that DECIDES.

PRs 6/9/10 built every fleet mechanism — admin drain, rolling restart,
circuit breakers, `/metrics`, the flight recorder — but scaling stayed
manual.  This module closes the loop: an `Autoscaler` runs beside the
`Router` and, every `FLAGS_autoscale_interval` seconds, reads the fleet's
own observability surface (the per-replica probe snapshots the router
already maintains from `/healthz`: queue depth, `drain_estimate_s`,
`deadline_miss_rate` EWMA, `tokens_per_step`, `page_free_frac`) and
spawns or drains `ReplicaProcess` workers to hold the SLO.

Control law (every threshold is a `FLAGS_autoscale_*` flag):

- **Pressure** (wants UP), any of: no ready replica; the fleet's BEST
  drain estimate above `up_drain_s` (every replica already owes that much
  wall time); mean queued requests per ready replica above
  `up_queue_depth`; any replica's deadline-miss-rate EWMA above
  `up_miss_rate`; any replica's KV page-pool free fraction below
  `min_page_free`.
- **Idle** (wants DOWN), all of: fleet above `min_replicas`, every ready
  replica's drain estimate under `down_drain_s`, no queued or active
  work anywhere, and the miss-rate EWMA back under the bar.
- **Hysteresis**: a want must persist `up_ticks` / `down_ticks`
  consecutive ticks before it acts (asymmetric: idling away a warm
  replica is costlier to undo than spawning one).
- **Per-direction cooldowns**: after ANY action, scale-up waits
  `up_cooldown` and scale-down `down_cooldown` before acting again — the
  new replica's probes must land before the loop re-judges the fleet.
- **Band**: the fleet never leaves [`min_replicas`, `max_replicas`].

Scale-UP spawns a `ReplicaProcess` (or the injected `spawn_fn`) with a
`--tp` degree chosen by `choose_tp()` from the devices no live replica
has claimed, then registers it with `Router.add_replica` — the replica
enters 'connecting' and takes no traffic until its probe reports ready.
The `autoscale.spawn` fault point fires inside the spawn path, so chaos
soaks drill the failed-scale-up branch (absorb, count, retry after the
cooldown).

Scale-DOWN rides the SAME admin-drain path as `rolling_restart`:
`set_admin_draining(True)` (the router stops picking it), poll the probe
until in-flight work finishes (bounded by `FLAGS_serve_drain_grace`),
only then deregister and terminate — exactly-once resolution is
preserved because no request is ever aborted by the controller.

Every scaling decision is a flight-recorder event (kind ``autoscale``)
carrying the signal vector that justified it, a trace span
(``autoscaler.scale_up`` / ``autoscaler.scale_down``), and a profiler
counter (`paddle_autoscaler_*` on /metrics) — a soak post-mortem replays
the controller's reasoning from any dump.
"""

from __future__ import annotations

import itertools
import threading
import time

from .. import profiler as _prof
from ..framework import core as _core
from ..obs import flight as _flight
from ..obs import trace as _obs
from .replica import Replica, ReplicaProcess

# snapshot keys every decision event carries into the flight ring (the
# full signal vector, rounded — a dump must justify the decision alone)
_SIGNAL_KEYS = (
    "replicas", "ready", "min_drain_s", "max_drain_s", "mean_queue",
    "max_miss_rate", "min_page_free", "busy", "idle_tokens_per_s",
)


def load_signals(snapshots, role=None):
    """Fold per-replica probe snapshots into the fleet signal vector the
    control law reads.  Pure (unit-testable without a router): draining
    and down replicas count toward fleet size but not toward load — a
    fleet of one dead replica reads as ready=0, which is pressure.

    `role` restricts the fold to one serving role's band (ISSUE 19): a
    disaggregated fleet runs one controller per role, each scaling its
    own slice on its own signals — prefill bands feel compute backlog,
    decode bands feel page starvation — without double-counting the
    other's replicas against its [min, max] band."""
    if role is not None:
        snapshots = [
            s for s in snapshots if s.get("role", "colocated") == role
        ]
    ready = [
        s for s in snapshots
        if s["state"] == "ready" and not s["admin_draining"]
    ]
    n = len(ready)
    return {
        "replicas": len(snapshots),
        "ready": n,
        "min_drain_s": min((s["drain_estimate_s"] for s in ready), default=0.0),
        "max_drain_s": max((s["drain_estimate_s"] for s in ready), default=0.0),
        "mean_queue": (sum(s["queue_depth"] for s in ready) / n) if n else 0.0,
        "max_miss_rate": max(
            (s.get("deadline_miss_rate", 0.0) for s in ready), default=0.0
        ),
        "min_page_free": min(
            (s.get("page_free_frac", 1.0) for s in ready), default=1.0
        ),
        "busy": any(s["queue_depth"] or s["active_slots"] for s in ready),
        # the cost signal (ROADMAP item 3): decode capacity sitting idle
        # RIGHT NOW, in tokens/s — per idle ready replica, its per-step
        # token yield over its EWMA step time.  What a scale-down would
        # reclaim; 0.0 when every ready replica holds work (reclaiming a
        # busy replica is not a cost win, it is a capacity loss)
        "idle_tokens_per_s": sum(
            s.get("tokens_per_step", 1.0) * (1e3 / ewma)
            for s in ready
            if not (s["queue_depth"] or s["active_slots"])
            and (ewma := s.get("decode_ewma_ms", 0.0)) > 0
        ),
    }


def decide(sig, cfg):
    """One pure control-law evaluation: (want, reason).  `want` is "up",
    "down", or "hold"; `reason` names the FIRST signal that justified it
    (the string every flight event and span carries).  Hysteresis and
    cooldowns are the caller's job — this is the memoryless core."""
    if sig["replicas"] < cfg["max_replicas"]:
        if sig["ready"] == 0:
            return "up", "no ready replica"
        if sig["min_drain_s"] > cfg["up_drain_s"]:
            return "up", (
                f"best drain {sig['min_drain_s']:.2f}s > {cfg['up_drain_s']}s"
            )
        if sig["mean_queue"] > cfg["up_queue_depth"]:
            return "up", (
                f"mean queue {sig['mean_queue']:.1f} > {cfg['up_queue_depth']}"
            )
        if sig["max_miss_rate"] > cfg["up_miss_rate"]:
            return "up", (
                f"miss rate {sig['max_miss_rate']:.3f} > {cfg['up_miss_rate']}"
            )
        if sig["min_page_free"] < cfg["min_page_free"]:
            return "up", (
                f"page free {sig['min_page_free']:.3f} < {cfg['min_page_free']}"
            )
    if (
        sig["replicas"] > cfg["min_replicas"]
        and sig["ready"] > cfg["min_replicas"]
        and not sig["busy"]
        and sig["max_drain_s"] <= cfg["down_drain_s"]
        and sig["max_miss_rate"] <= cfg["up_miss_rate"]
        # the $/token gate (ROADMAP item 3): only shrink when the fleet is
        # actually wasting decode capacity — emptiness alone does not
        # justify a drain when the reclaimable idle throughput is below
        # the configured floor (0.0 keeps the pure-emptiness behavior)
        and sig.get("idle_tokens_per_s", 0.0)
        >= cfg.get("down_min_idle_tokens_s", 0.0)
    ):
        reason = (
            f"idle: max drain {sig['max_drain_s']:.2f}s <= "
            f"{cfg['down_drain_s']}s, no queued/active work"
        )
        idle_tok = sig.get("idle_tokens_per_s", 0.0)
        if idle_tok > 0:
            chips = max(1, int(cfg.get("chips", 1)))
            reason += (
                f", reclaim {idle_tok / chips:.1f} idle tokens/s/chip"
            )
        return "down", reason
    return "hold", "within band"


def choose_tp(free_devices, tp_max, kv_heads=None):
    """TP degree for a new replica: the largest power of two that fits the
    unclaimed devices, clamped by `tp_max` and (when given) dividing
    `kv_heads` — the same divisibility contract engine construction
    enforces with a typed ShardingError.  Always >= 1: a fleet out of
    free devices still spawns a single-device replica (oversubscription
    beats an under-provisioned fleet on CPU and is probed-before-picked
    everywhere)."""
    tp = 1
    cap = max(1, min(int(free_devices), int(tp_max)))
    while tp * 2 <= cap and (kv_heads is None or kv_heads % (tp * 2) == 0):
        tp *= 2
    return tp


class Autoscaler:
    """The closed loop.  Construct over a started `Router`, then either
    `start()` the background control thread or drive `tick()` inline
    (tests and the soak harness do the latter with an explicit clock).

    `spawn_fn(index, tp)` must return a ready-to-register `Replica`
    (default: boot a `ReplicaProcess` subprocess worker and wrap it);
    `stop_fn(replica)` tears one down after its drain (default: SIGTERM
    the managed process).  Injecting both keeps the control law testable
    with in-process replicas — the loop itself never cares which."""

    def __init__(self, router, spawn_fn=None, stop_fn=None, *,
                 min_replicas=None, max_replicas=None, interval=None,
                 up_ticks=None, down_ticks=None, up_cooldown=None,
                 down_cooldown=None, up_drain_s=None, up_queue_depth=None,
                 up_miss_rate=None, min_page_free=None, down_drain_s=None,
                 down_min_idle_tokens_s=None, tp_max=None, devices_total=None,
                 kv_heads=None, drain_grace=None, log_dir=None, journal=None,
                 role=None):
        f = _core.flag

        def _pick(v, name, cast):
            return cast(v if v is not None else f(name))

        self.router = router
        self._spawn_fn = spawn_fn
        self._stop_fn = stop_fn
        # disaggregated fleets (ISSUE 19) run ONE controller per role:
        # this instance reads only its role's signals, drains only its
        # role's replicas, and spawns workers booted into that role
        self.role = None if role is None else str(role)
        self.min_replicas = _pick(min_replicas, "FLAGS_autoscale_min_replicas", int)
        self.max_replicas = _pick(max_replicas, "FLAGS_autoscale_max_replicas", int)
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"bad replica band [{self.min_replicas}, {self.max_replicas}]"
            )
        self.interval = _pick(interval, "FLAGS_autoscale_interval", float)
        self.up_ticks = _pick(up_ticks, "FLAGS_autoscale_up_ticks", int)
        self.down_ticks = _pick(down_ticks, "FLAGS_autoscale_down_ticks", int)
        self.up_cooldown = _pick(up_cooldown, "FLAGS_autoscale_up_cooldown", float)
        self.down_cooldown = _pick(
            down_cooldown, "FLAGS_autoscale_down_cooldown", float)
        self.cfg = {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "up_drain_s": _pick(up_drain_s, "FLAGS_autoscale_up_drain_s", float),
            "up_queue_depth": _pick(
                up_queue_depth, "FLAGS_autoscale_up_queue_depth", float),
            "up_miss_rate": _pick(
                up_miss_rate, "FLAGS_autoscale_up_miss_rate", float),
            "min_page_free": _pick(
                min_page_free, "FLAGS_autoscale_min_page_free", float),
            "down_drain_s": _pick(
                down_drain_s, "FLAGS_autoscale_down_drain_s", float),
            "down_min_idle_tokens_s": _pick(
                down_min_idle_tokens_s,
                "FLAGS_autoscale_down_idle_tokens_s", float),
        }
        self.tp_max = _pick(tp_max, "FLAGS_autoscale_tp_max", int)
        if devices_total is None:
            try:
                import jax
                devices_total = jax.device_count()
            except Exception:
                devices_total = 1
        self.devices_total = int(devices_total)
        self.cfg["chips"] = max(1, self.devices_total)
        self.kv_heads = kv_heads
        self.drain_grace = float(
            drain_grace if drain_grace is not None
            else f("FLAGS_serve_drain_grace")
        )
        self.log_dir = log_dir
        # device claims: every pre-existing replica is assumed tp=1 (the
        # probe snapshot carries no degree); managed spawns record theirs
        self._claimed = {r.rid: 1 for r in router.replicas}
        self._managed = {}  # rid -> Replica, spawn order preserved
        self._seq = itertools.count()
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t = None  # monotonic time of the last up/down
        # durable control plane (ISSUE 17): default to the router's journal
        # so a journaled router automatically journals its controller too.
        # A RESUMED journal restores the cooldown clock — the journaled
        # wall-clock elapsed-since-last-action maps onto THIS process's
        # monotonic clock, so a successor does not flap the fleet the
        # moment it takes over (the primary's cooldown still binds).
        self.journal = (
            journal if journal is not None
            else getattr(router, "journal", None)
        )
        if self.journal is not None and self.journal.resumed:
            st = self.journal.state_snapshot().get("autoscale")
            if st:
                elapsed = max(0.0, time.time() - st["last_action_wall"])
                self._last_action_t = time.monotonic() - elapsed
                self._up_streak = int(st.get("up_streak", 0))
                self._down_streak = int(st.get("down_streak", 0))
                _flight.record(
                    "autoscale",
                    f"cooldown clock restored from journal "
                    f"({elapsed:.2f}s since last action)",
                    band=st.get("band"),
                )
        # one control lock serializes ticks: the background loop and any
        # inline tick() caller (tests, the soak harness) never interleave
        # a decision — scale actions are strictly sequential
        self._ctl_mu = threading.Lock()
        self._stop_ev = threading.Event()
        self._thread = None

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._stop_ev.clear()
        t = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True
        )
        self._thread = t
        t.start()
        return self

    def stop(self):
        self._stop_ev.set()
        t = self._thread
        if t is not None:
            t.join(max(5.0, self.drain_grace + 5.0))
        self._thread = None

    def _loop(self):
        while not self._stop_ev.wait(self.interval):
            try:
                self.tick()
            except Exception as e:  # the loop must outlive one bad tick
                _flight.record("autoscale", f"tick error: {e}")

    # -- control law ---------------------------------------------------------

    def tick(self, now=None):
        """One control tick: read signals, apply hysteresis + cooldowns,
        act.  Returns {"want", "action", "reason", "signals"} so tests and
        the soak harness can assert the loop's reasoning directly.
        Serialized by _ctl_mu against the background loop."""
        with self._ctl_mu:
            return self._tick_locked(
                time.monotonic() if now is None else now
            )

    def _tick_locked(self, now):
        _prof.record_autoscale_event("ticks")
        self._reap_dead(now)
        sig = load_signals(
            [rep.snapshot() for rep in self.router.replicas], role=self.role
        )
        _prof.record_autoscale_replicas(sig["replicas"])
        want, reason = decide(sig, self.cfg)
        if want == "up":
            self._up_streak += 1
            self._down_streak = 0
        elif want == "down":
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        action = "hold"
        if (
            want == "up"
            and self._up_streak >= self.up_ticks
            and self._cooled(now, self.up_cooldown)
        ):
            action = "up" if self._scale_up(sig, reason) else "hold"
        elif (
            want == "down"
            and self._down_streak >= self.down_ticks
            and self._cooled(now, self.down_cooldown)
        ):
            action = "down" if self._scale_down(sig, reason) else "hold"
        if action == "hold":
            _prof.record_autoscale_event("holds")
        else:
            self._last_action_t = now
            self._up_streak = self._down_streak = 0
            if self.journal is not None:
                # journal the band + cooldown clock in WALL time (monotonic
                # clocks do not survive process death): a successor maps
                # elapsed-since-action back onto its own monotonic clock
                self.journal.append(
                    "autoscale",
                    band=[self.min_replicas, self.max_replicas],
                    last_action_wall=time.time(),
                    up_streak=self._up_streak,
                    down_streak=self._down_streak,
                )
        return {"want": want, "action": action, "reason": reason,
                "signals": sig}

    def _reap_dead(self, now):
        """Deregister MANAGED workers whose subprocess died (chaos kill -9,
        crash): a dead registration would count toward the band and pin the
        fleet at max_replicas with less-than-max live capacity — the loop
        could never replace what the chaos took.  Seed replicas the
        operator registered stay put: `rolling_restart` owns their respawn
        path (the Container revives the same process slot)."""
        for rid, rep in list(self._managed.items()):
            if rep.process is None or rep.process.alive():
                continue
            try:
                self.router.remove_replica(rid)
            except KeyError:
                pass
            self._managed.pop(rid, None)
            self._claimed.pop(rid, None)
            _prof.record_autoscale_event("reaps")
            _prof.record_autoscale_replicas(len(self.router.replicas))
            _flight.record(
                "autoscale", f"reaped dead replica {rid}",
                fleet=len(self.router.replicas),
            )

    def _cooled(self, now, cooldown):
        return self._last_action_t is None or (
            now - self._last_action_t >= cooldown
        )

    def _free_devices(self):
        return max(0, self.devices_total - sum(self._claimed.values()))

    def _event_fields(self, sig):
        return {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in sig.items() if k in _SIGNAL_KEYS
        }

    # -- actions -------------------------------------------------------------

    def _scale_up(self, sig, reason):
        """Spawn + register one replica.  False on spawn failure (counted,
        recorded, retried after the cooldown) — a chaos-armed
        `autoscale.spawn` fault lands here, not in the control thread's
        lap."""
        from ..fault import injection as _inj

        tid, sid = _obs.new_trace_id(), _obs.new_span_id()
        t0 = time.perf_counter()
        idx = next(self._seq)
        tp = choose_tp(self._free_devices(), self.tp_max, self.kv_heads)
        try:
            _inj.inject("autoscale.spawn", context=f"as{idx}")
            rep = (
                self._spawn_fn(idx, tp) if self._spawn_fn is not None
                else self._default_spawn(idx, tp)
            )
            self.router.add_replica(rep)
        except Exception as e:
            _prof.record_autoscale_event("spawn_failures")
            _flight.record(
                "autoscale", f"scale_up FAILED: {e}", reason=reason, tp=tp,
                **self._event_fields(sig),
            )
            _obs.record(
                "autoscaler.scale_up", tid, t0=t0, t1=time.perf_counter(),
                span_id=sid, status="error", error=f"{type(e).__name__}: {e}",
                tp=tp,
            )
            return False
        self._managed[rep.rid] = rep
        self._claimed[rep.rid] = tp
        _prof.record_autoscale_event("scale_ups")
        _prof.record_autoscale_replicas(len(self.router.replicas))
        _flight.record(
            "autoscale", f"scale_up -> {rep.rid}", reason=reason, tp=tp,
            fleet=len(self.router.replicas), **self._event_fields(sig),
        )
        _obs.record(
            "autoscaler.scale_up", tid, t0=t0, t1=time.perf_counter(),
            span_id=sid, status="ok", replica=rep.rid, tp=tp, reason=reason,
        )
        return True

    def _scale_down(self, sig, reason):
        """Drain + deregister one replica through the admin-drain path
        (exactly-once: the router stops picking it, in-flight work
        finishes, ONLY then is the worker stopped)."""
        rep = self._pick_victim()
        if rep is None:
            return False
        tid, sid = _obs.new_trace_id(), _obs.new_span_id()
        t0 = time.perf_counter()
        rep.set_admin_draining(True)
        drained = False
        deadline = time.monotonic() + self.drain_grace
        while time.monotonic() < deadline:
            h = rep.probe()
            if h is None or (
                not h.get("active_slots") and not h.get("queue_depth")
            ):
                drained = True
                break
            time.sleep(0.05)
        self.router.remove_replica(rep.rid)
        self._managed.pop(rep.rid, None)
        self._claimed.pop(rep.rid, None)
        # the decision is complete at deregistration: count it BEFORE the
        # worker teardown below, which can block for seconds
        _prof.record_autoscale_event("scale_downs")
        _prof.record_autoscale_replicas(len(self.router.replicas))
        try:
            if self._stop_fn is not None:
                self._stop_fn(rep)
            elif rep.process is not None:
                rep.process.terminate()
        except Exception as e:
            _flight.record("autoscale", f"stop {rep.rid} failed: {e}")
        _flight.record(
            "autoscale", f"scale_down -> {rep.rid}", reason=reason,
            drained=drained, fleet=len(self.router.replicas),
            **self._event_fields(sig),
        )
        _obs.record(
            "autoscaler.scale_down", tid, t0=t0, t1=time.perf_counter(),
            span_id=sid, status="ok" if drained else "forced",
            replica=rep.rid, reason=reason,
        )
        return True

    def _pick_victim(self):
        """Least-loaded ready replica, managed spawns first (LIFO within
        the tie) — the seed fleet the operator registered by hand is the
        last thing the controller drains, and never below the band."""
        cands = []
        for i, rep in enumerate(self.router.replicas):
            s = rep.snapshot()
            if s["state"] != "ready" or s["admin_draining"]:
                continue
            if (
                self.role is not None
                and s.get("role", "colocated") != self.role
            ):
                continue
            cands.append((
                0 if rep.rid in self._managed else 1,
                s["queue_depth"] + s["active_slots"],
                -i,  # LIFO: newest spawn drains first on ties
                rep,
            ))
        ready = len(cands)
        if ready <= self.min_replicas:
            return None
        cands.sort(key=lambda c: c[:3])
        return cands[0][3]

    def _default_spawn(self, idx, tp):
        """Boot a ReplicaProcess worker on a free port and wait for its
        port to accept (readiness itself is probe-driven: the router only
        picks it after /healthz says ready)."""
        import socket
        import tempfile

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        log_dir = self.log_dir or tempfile.mkdtemp(prefix="autoscale_log_")
        extra = ["--tp", str(tp)] if tp > 1 else []
        if self.role is not None and self.role != "colocated":
            extra += ["--role", self.role]
        proc = ReplicaProcess(
            index=100 + idx, port=port, log_dir=log_dir, extra_args=extra,
        ).start()
        return Replica(f"as{idx}", proc.url, process=proc)
