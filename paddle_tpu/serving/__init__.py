"""Multi-replica serving layer (ISSUE 9): a front-end router over N
engine replicas with health-checked failover, deadline propagation,
per-replica circuit breakers, brownout shedding, and rolling
drain/restart orchestration.  See router.py for the routing contract and
replica.py for the replica handle / managed worker process.

The heavy pieces load lazily: importing `paddle_tpu.serving` must not pull
the model stack (mirrors inference/__init__'s engine export pattern).
"""

from __future__ import annotations

__all__ = [
    "Router",
    "RouterError",
    "RouterCrashed",
    "RouterStandby",
    "NoReadyReplica",
    "RouterOverloaded",
    "NoDecodeCapacity",
    "DeadlineExhausted",
    "serve_router",
    "Journal",
    "JournalCorruption",
    "IdempotencyCache",
    "Replica",
    "ReplicaProcess",
    "ReplicaTransportError",
    "Autoscaler",
    "Workload",
    "SoakReport",
    "run_soak",
]


def __getattr__(name):
    if name in (
        "Router", "RouterError", "RouterCrashed", "RouterStandby",
        "NoReadyReplica", "RouterOverloaded", "NoDecodeCapacity",
        "DeadlineExhausted", "serve_router",
    ):
        from . import router as _router

        return getattr(_router, name)
    if name in ("Journal", "JournalCorruption", "IdempotencyCache"):
        from . import journal as _journal

        return getattr(_journal, name)
    if name in ("Replica", "ReplicaProcess", "ReplicaTransportError"):
        from . import replica as _replica

        return getattr(_replica, name)
    if name == "Autoscaler":
        from . import autoscaler as _autoscaler

        return _autoscaler.Autoscaler
    if name in ("Workload", "SoakReport", "run_soak"):
        from . import workload as _workload

        return getattr(_workload, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
