"""Reduction / scan ops (reference: python/paddle/tensor/math.py & stat.py)."""

from __future__ import annotations

import builtins

import numpy as np
import jax.numpy as jnp

from ..tensor import Tensor
from ._factory import reduce_op
from .dispatch import apply, coerce

sum = reduce_op("sum", lambda a, ax, kd: jnp.sum(a, axis=ax, keepdims=kd))
mean = reduce_op("mean", lambda a, ax, kd: jnp.mean(a, axis=ax, keepdims=kd))
prod = reduce_op("prod", lambda a, ax, kd: jnp.prod(a, axis=ax, keepdims=kd))
max = reduce_op("max", lambda a, ax, kd: jnp.max(a, axis=ax, keepdims=kd))
min = reduce_op("min", lambda a, ax, kd: jnp.min(a, axis=ax, keepdims=kd))
amax = reduce_op("amax", lambda a, ax, kd: jnp.max(a, axis=ax, keepdims=kd))
amin = reduce_op("amin", lambda a, ax, kd: jnp.min(a, axis=ax, keepdims=kd))
all = reduce_op("all", lambda a, ax, kd: jnp.all(a.astype(bool), axis=ax, keepdims=kd))
any = reduce_op("any", lambda a, ax, kd: jnp.any(a.astype(bool), axis=ax, keepdims=kd))
nansum = reduce_op("nansum", lambda a, ax, kd: jnp.nansum(a, axis=ax, keepdims=kd))
nanmean = reduce_op("nanmean", lambda a, ax, kd: jnp.nanmean(a, axis=ax, keepdims=kd))
import jax.scipy.special as _jss

logsumexp = reduce_op(
    "logsumexp", lambda a, ax, kd: _jss.logsumexp(a, axis=ax, keepdims=kd)
)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = coerce(x)
    ddof = 1 if unbiased else 0
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(lambda a: jnp.var(a, axis=ax, ddof=ddof, keepdims=keepdim), [x], name="var")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = coerce(x)
    ddof = 1 if unbiased else 0
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(lambda a: jnp.std(a, axis=ax, ddof=ddof, keepdims=keepdim), [x], name="std")


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    x = coerce(x)
    return apply(lambda a: jnp.median(a, axis=axis, keepdims=keepdim), [x], name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim), [x], name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    x = coerce(x)
    return apply(
        lambda a: jnp.quantile(a, jnp.asarray(q), axis=axis, keepdims=keepdim, method=interpolation),
        [x],
        name="quantile",
    )


def count_nonzero(x, axis=None, keepdim=False, name=None):
    x = coerce(x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim), [x])


def cumsum(x, axis=None, dtype=None, name=None):
    x = coerce(x)
    if axis is None:
        return apply(lambda a: jnp.cumsum(a.reshape(-1)), [x], name="cumsum")
    return apply(lambda a: jnp.cumsum(a, axis=axis), [x], name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    x = coerce(x)
    if dim is None:
        return apply(lambda a: jnp.cumprod(a.reshape(-1)), [x], name="cumprod")
    return apply(lambda a: jnp.cumprod(a, axis=dim), [x], name="cumprod")


def cummax(x, axis=None, dtype="int64", name=None):
    x = coerce(x)
    ax = axis if axis is not None else 0
    xx = x if axis is not None else x.reshape([-1])
    vals = apply(lambda a: jnp.maximum.accumulate(a, axis=ax), [xx], name="cummax")
    idx = apply(
        lambda a: _cum_arg(a, ax, jnp.maximum), [xx.detach()], name="cummax_idx"
    )
    return vals, idx


def cummin(x, axis=None, dtype="int64", name=None):
    x = coerce(x)
    ax = axis if axis is not None else 0
    xx = x if axis is not None else x.reshape([-1])
    vals = apply(lambda a: jnp.minimum.accumulate(a, axis=ax), [xx], name="cummin")
    idx = apply(lambda a: _cum_arg(a, ax, jnp.minimum), [xx.detach()], name="cummin_idx")
    return vals, idx


def _cum_arg(a, ax, op):
    acc = op.accumulate(a, axis=ax)
    eq = a == acc
    n = a.shape[ax]
    ar = jnp.arange(n).reshape([-1 if i == (ax % a.ndim) else 1 for i in range(a.ndim)])
    idx = jnp.where(eq, ar, 0)
    return jnp.maximum.accumulate(idx, axis=ax)
