"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""

from __future__ import annotations

import builtins
import numpy as np
import jax
import jax.numpy as jnp

from ..framework import core as _core
from ..tensor import Tensor
from ._factory import inplace_variant
from .dispatch import apply, coerce, inplace_rebind, wrap


def _ints(v):
    if isinstance(v, Tensor):
        return [int(s) for s in v.numpy().tolist()]
    if isinstance(v, (int, np.integer)):
        return [int(v)]
    return [int(s._data) if isinstance(s, Tensor) else int(s) for s in v]


def cast(x, dtype, name=None):
    x = coerce(x)
    jdt = _core.to_jax_dtype(_core.convert_dtype(dtype))
    return apply(lambda a: a.astype(jdt), [x], name="cast")


cast_ = inplace_variant(cast)


def reshape(x, shape, name=None):
    x = coerce(x)
    shape = _ints(shape)
    return apply(lambda a: jnp.reshape(a, shape), [x], name="reshape")


reshape_ = inplace_variant(reshape)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def transpose(x, perm=None, name=None):
    x = coerce(x)
    if perm is None:
        perm = list(range(x.ndim))[::-1]
    perm = _ints(perm)
    return apply(lambda a: jnp.transpose(a, perm), [x], name="transpose")


transpose_ = inplace_variant(transpose)


def t(x, name=None):
    x = coerce(x)
    if x.ndim < 2:
        return assign_alias(x)
    return apply(lambda a: jnp.swapaxes(a, -1, -2), [x], name="t")


def assign_alias(x):
    return apply(lambda a: a, [coerce(x)], name="identity")


def moveaxis(x, source, destination, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.moveaxis(a, source, destination), [x], name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.swapaxes(a, axis0, axis1), [x], name="swapaxes")


transpose2 = swapaxes


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = coerce(x)
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    ea = stop_axis % nd if nd else 0

    def f(a):
        shape = a.shape
        newshape = shape[:sa] + (-1,) + shape[ea + 1 :]
        return jnp.reshape(a, newshape)

    return apply(f, [x], name="flatten")


flatten_ = inplace_variant(flatten)


def squeeze(x, axis=None, name=None):
    x = coerce(x)
    if axis is None:
        ax = None
    else:
        ax = tuple(a % builtins.max(x.ndim, 1) for a in _ints(axis) )
        ax = tuple(a for a in ax if x.shape[a] == 1)
    return apply(lambda a: jnp.squeeze(a, ax), [x], name="squeeze")


squeeze_ = inplace_variant(squeeze)


def unsqueeze(x, axis, name=None):
    x = coerce(x)
    ax = _ints(axis)
    return apply(lambda a: jnp.expand_dims(a, ax), [x], name="unsqueeze")


unsqueeze_ = inplace_variant(unsqueeze)


def concat(x, axis=0, name=None):
    xs = [coerce(v) for v in x]
    if isinstance(axis, Tensor):
        axis = int(axis.numpy())
    return apply(lambda *arrs: jnp.concatenate(arrs, axis=axis), xs, name="concat")


def stack(x, axis=0, name=None):
    xs = [coerce(v) for v in x]
    return apply(lambda *arrs: jnp.stack(arrs, axis=axis), xs, name="stack")


def unstack(x, axis=0, num=None, name=None):
    x = coerce(x)
    n = num or x.shape[axis]
    return list(
        apply(
            lambda a: tuple(jnp.moveaxis(a, axis, 0)[i] for i in range(n)),
            [x],
            multi=True,
            name="unstack",
        )
    )


def unbind(input, axis=0, name=None):
    return unstack(input, axis)


def split(x, num_or_sections, axis=0, name=None):
    x = coerce(x)
    if isinstance(axis, Tensor):
        axis = int(axis.numpy())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = _ints(num_or_sections)
        n_unknown = builtins.sum(1 for s in sizes if s < 0)
        if n_unknown:
            known = builtins.sum(s for s in sizes if s >= 0)
            sizes = [s if s >= 0 else dim - known for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def f(a):
        return tuple(
            jax.lax.slice_in_dim(a, o, o + s, axis=axis) for o, s in zip(offsets, sizes)
        )

    return list(apply(f, [x], multi=True, name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tensor_split(x, num_or_indices, axis=0, name=None):
    x = coerce(x)
    dim = x.shape[axis]
    if isinstance(num_or_indices, int):
        base, rem = divmod(dim, num_or_indices)
        sizes = [base + (1 if i < rem else 0) for i in range(num_or_indices)]
        return split(x, sizes, axis)
    idx = _ints(num_or_indices)
    sizes = []
    prev = 0
    for i in idx:
        sizes.append(i - prev)
        prev = i
    sizes.append(dim - prev)
    return split(x, sizes, axis)


def tile(x, repeat_times, name=None):
    x = coerce(x)
    reps = _ints(repeat_times)
    return apply(lambda a: jnp.tile(a, reps), [x], name="tile")


def expand(x, shape, name=None):
    x = coerce(x)
    shape = _ints(shape)
    cur = x.shape
    full = list(shape)
    # -1 entries keep the original dim
    off = len(full) - len(cur)
    for i, s in enumerate(full):
        if s == -1:
            full[i] = cur[i - off]
    return apply(lambda a: jnp.broadcast_to(a, full), [x], name="expand")


def expand_as(x, y, name=None):
    return expand(x, coerce(y).shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    xs = [coerce(v) for v in inputs]
    return list(apply(lambda *arrs: tuple(jnp.broadcast_arrays(*arrs)), xs, multi=True))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def flip(x, axis, name=None):
    x = coerce(x)
    ax = _ints(axis)
    return apply(lambda a: jnp.flip(a, ax), [x], name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    x = coerce(x)
    return apply(lambda a: jnp.rot90(a, k, axes), [x], name="rot90")


def roll(x, shifts, axis=None, name=None):
    x = coerce(x)
    sh = _ints(shifts) if not isinstance(shifts, int) else shifts
    ax = _ints(axis) if axis is not None and not isinstance(axis, int) else axis
    if isinstance(sh, list) and len(sh) == 1:
        sh = sh[0]
    if isinstance(ax, list) and len(ax) == 1:
        ax = ax[0]
    return apply(lambda a: jnp.roll(a, sh, ax), [x], name="roll")


def slice(input, axes, starts, ends, name=None):
    x = coerce(input)
    axes, starts, ends = _ints(axes), _ints(starts), _ints(ends)

    def f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = builtins.slice(s, e)
        return a[tuple(idx)]

    return apply(f, [x], name="slice")


def crop(x, shape=None, offsets=None, name=None):
    x = coerce(x)
    shape = _ints(shape)
    offsets = _ints(offsets) if offsets is not None else [0] * len(shape)

    def f(a):
        idx = tuple(
            builtins.slice(o, o + (s if s != -1 else a.shape[i] - o))
            for i, (o, s) in enumerate(zip(offsets, shape))
        )
        return a[idx]

    return apply(f, [x], name="crop")


def gather(x, index, axis=0, name=None):
    x, index = coerce(x), coerce(index)
    if isinstance(axis, Tensor):
        axis = int(axis.numpy())
    return apply(lambda a, i: jnp.take(a, i.astype(jnp.int32), axis=axis), [x, index], name="gather")


def gather_nd(x, index, name=None):
    x, index = coerce(x), coerce(index)

    def f(a, i):
        i = i.astype(jnp.int32)
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a[idx]

    return apply(f, [x, index], name="gather_nd")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    arr, indices = coerce(arr), coerce(indices)
    return apply(
        lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=axis),
        [arr, indices],
        name="take_along_axis",
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    arr, indices = coerce(arr), coerce(indices)
    values = coerce(values)

    def f(a, i, v):
        i = i.astype(jnp.int32)
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        dims = [jnp.arange(s) for s in i.shape]
        grids = jnp.meshgrid(*dims, indexing="ij")
        idx = tuple(grids[d] if d != axis else i for d in range(a.ndim))
        if reduce == "assign":
            return a.at[idx].set(v)
        if reduce in ("add", "sum"):
            return a.at[idx].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[idx].multiply(v)
        raise ValueError(reduce)

    return apply(f, [arr, indices, values], name="put_along_axis")


def scatter(x, index, updates, overwrite=True, name=None):
    x, index, updates = coerce(x), coerce(index), coerce(updates)

    def f(a, i, u):
        i = i.astype(jnp.int32).reshape(-1)
        if overwrite:
            return a.at[i].set(u.astype(a.dtype))
        return a.at[i].add(u.astype(a.dtype))

    return apply(f, [x, index, updates], name="scatter")


scatter_ = inplace_variant(scatter)


def scatter_nd_add(x, index, updates, name=None):
    x, index, updates = coerce(x), coerce(index), coerce(updates)

    def f(a, i, u):
        idx = tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))
        return a.at[idx].add(u.astype(a.dtype))

    return apply(f, [x, index, updates], name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    index, updates = coerce(index), coerce(updates)
    shape = _ints(shape)

    def f(i, u):
        z = jnp.zeros(shape, u.dtype)
        idx = tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))
        return z.at[idx].add(u)

    return apply(f, [index, updates], name="scatter_nd")


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    x, index = coerce(x), coerce(index)
    return apply(
        lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=1),
        [x, index],
        name="index_sample",
    )


def index_add(x, index, axis, value, name=None):
    x, index, value = coerce(x), coerce(index), coerce(value)

    def f(a, i, v):
        i = i.astype(jnp.int32)
        a2 = jnp.moveaxis(a, axis, 0)
        v2 = jnp.moveaxis(v, axis, 0)
        out = a2.at[i].add(v2.astype(a.dtype))
        return jnp.moveaxis(out, 0, axis)

    return apply(f, [x, index, value], name="index_add")


index_add_ = inplace_variant(index_add)


def index_put(x, indices, value, accumulate=False, name=None):
    x = coerce(x)
    idx_ts = [coerce(i) for i in indices]
    value = coerce(value)

    def f(a, v, *idx):
        key = tuple(i.astype(jnp.int32) if jnp.issubdtype(i.dtype, jnp.integer) else i for i in idx)
        if accumulate:
            return a.at[key].add(v.astype(a.dtype))
        return a.at[key].set(v.astype(a.dtype))

    return apply(f, [x, value] + idx_ts, name="index_put")


def masked_select(x, mask, name=None):
    x, mask = coerce(x), coerce(mask)
    # dynamic output shape: eager-only (documented; mirror of reference's
    # masked_select which is also shape-dynamic)
    return wrap(x._data[mask._data.astype(bool)])


def masked_fill(x, mask, value, name=None):
    x, mask = coerce(x), coerce(mask)
    if isinstance(value, Tensor):
        return apply(
            lambda a, m, v: jnp.where(m.astype(bool), v.astype(a.dtype), a),
            [x, mask, value],
            name="masked_fill",
        )
    return apply(
        lambda a, m: jnp.where(m.astype(bool), jnp.asarray(value, a.dtype), a),
        [x, mask],
        name="masked_fill",
    )


masked_fill_ = inplace_variant(masked_fill)


def where(condition, x=None, y=None, name=None):
    condition = coerce(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    x, y = coerce(x), coerce(y)
    return apply(
        lambda c, a, b: jnp.where(c.astype(bool), a, b), [condition, x, y], name="where"
    )


def nonzero(x, as_tuple=False, name=None):
    x = coerce(x)
    arr = np.asarray(x._data)  # dynamic shape → host (eager only)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(wrap(jnp.asarray(i)) for i in nz)
    return wrap(jnp.asarray(np.stack(nz, axis=1)))


def repeat_interleave(x, repeats, axis=None, name=None):
    x = coerce(x)
    if isinstance(repeats, Tensor):
        reps = repeats._data

        def f(a, r):
            return jnp.repeat(a, r, axis=axis, total_repeat_length=int(np.sum(np.asarray(r))))

        return apply(f, [x, repeats], name="repeat_interleave")
    return apply(lambda a: jnp.repeat(a, repeats, axis=axis), [x], name="repeat_interleave")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = coerce(x)
    pad = _ints(pad)
    nd = x.ndim

    if len(pad) == 2 * nd:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle convention: pad applies to last len(pad)//2 spatial dims,
        # ordered from last dim backwards: [left,right, top,bottom, ...]
        width = [(0, 0)] * nd
        npairs = len(pad) // 2
        if data_format.upper().endswith("C"):  # NHWC / NLC / NDHWC
            spatial = list(range(1, 1 + npairs))
        else:  # NCHW-style
            spatial = list(range(nd - npairs, nd))
        for k, axis_i in enumerate(reversed(spatial)):
            width[axis_i] = (pad[2 * k], pad[2 * k + 1])

    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]

    def f(a):
        if jmode == "constant":
            return jnp.pad(a, width, mode="constant", constant_values=value)
        return jnp.pad(a, width, mode=jmode)

    return apply(f, [x], name="pad")


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = coerce(x)
    axes, starts, ends, strides = _ints(axes), _ints(starts), _ints(ends), _ints(strides)

    def f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(s, e, st)
        return a[tuple(idx)]

    return apply(f, [x], name="strided_slice")


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = coerce(x)
    arr = np.asarray(x._data)
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse, return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return wrap(jnp.asarray(res))
    outs = [wrap(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = coerce(x)
    arr = np.asarray(x._data)
    vals = []
    counts = []
    flat = arr.flatten() if axis is None else arr
    prev = None
    for v in flat:
        if prev is None or v != prev:
            vals.append(v)
            counts.append(1)
        else:
            counts[-1] += 1
        prev = v
    outs = [wrap(jnp.asarray(np.array(vals)))]
    if return_inverse:
        inv = np.concatenate([[i] * c for i, c in enumerate(counts)]) if counts else np.array([], dtype=np.int32)
        outs.append(wrap(jnp.asarray(inv)))
    if return_counts:
        outs.append(wrap(jnp.asarray(np.array(counts))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def fill_diagonal_(x, value, offset=0, wrap_=False, name=None):
    def f(a):
        n = builtins.min(a.shape[-2], a.shape[-1])
        idx = jnp.arange(n - builtins.abs(offset))
        r = idx + builtins.max(-offset, 0)
        c = idx + builtins.max(offset, 0)
        return a.at[..., r, c].set(value)

    return inplace_rebind(x, apply(f, [coerce(x)], name="fill_diagonal"))


def fill_(x, value):
    return inplace_rebind(x, apply(lambda a: jnp.full_like(a, value), [coerce(x)], name="fill"))


def zero_(x):
    return fill_(x, 0.0)


def one_hot(x, num_classes, name=None):
    x = coerce(x)
    return apply(
        lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes, dtype=jnp.float32),
        [x],
        name="one_hot",
    )


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    """(1-eps)*label + eps*prior (uniform 1/num_classes if no prior) —
    reference: paddle.nn.functional.label_smooth."""
    label = coerce(label)
    if prior_dist is not None:
        prior = coerce(prior_dist)
        return apply(
            lambda l, p: (1.0 - epsilon) * l + epsilon * p.astype(l.dtype),
            [label, prior],
            name="label_smooth",
        )
    return apply(
        lambda l: (1.0 - epsilon) * l + epsilon / l.shape[-1],
        [label],
        name="label_smooth",
    )


def set_value_(x, value):
    """Replace payload (used by optimizers / state loading)."""
    value = coerce(value)
    x._data = value._data.astype(x._data.dtype)
    return x


def bincount(x, weights=None, minlength=0, name=None):
    x = coerce(x)
    if weights is not None:
        weights = coerce(weights)
        length = int(builtins.max(int(np.asarray(x._data).max(initial=0)) + 1, minlength))
        return apply(
            lambda a, w: jnp.bincount(a.astype(jnp.int32), w, length=length),
            [x, weights],
            name="bincount",
        )
    length = int(builtins.max(int(np.asarray(x._data).max(initial=0)) + 1, minlength))
    return apply(lambda a: jnp.bincount(a.astype(jnp.int32), length=length), [x], name="bincount")


def histogram(input, bins=100, min=0, max=0, name=None):
    x = coerce(input)
    arr = np.asarray(x._data)
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    h, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return wrap(jnp.asarray(h))


def as_strided(x, shape, stride, offset=0, name=None):
    x = coerce(x)
    arr = np.lib.stride_tricks.as_strided(
        np.asarray(x._data).reshape(-1)[offset:],
        shape=shape,
        strides=[s * x.element_size() for s in stride],
    )
    return wrap(jnp.asarray(arr.copy()))


def view_as(x, other, name=None):
    return reshape(x, coerce(other).shape)


# ---------------------------------------------------------------------------
# long-tail manipulation ops (round 4: §2.3 API-breadth pass)
# ---------------------------------------------------------------------------


def hsplit(x, num_or_indices, name=None):
    """Split along axis 1 (axis 0 for 1-D), numpy semantics."""
    x = coerce(x)
    axis = 0 if len(x.shape) == 1 else 1
    return split(x, num_or_indices, axis=axis)


def vsplit(x, num_or_indices, name=None):
    x = coerce(x)
    return split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    x = coerce(x)
    return split(x, num_or_indices, axis=2)


def permute(x, *perm):
    """torch-compat alias of transpose (also a Tensor method upstream)."""
    if len(perm) == 1 and isinstance(perm[0], (list, tuple)):
        perm = tuple(perm[0])
    return transpose(coerce(x), list(perm))


def take(x, index, mode="raise", name=None):
    """Flat-index gather (reference: paddle.take)."""
    x, index = coerce(x), coerce(index)

    def f(a, i):
        flat = a.reshape(-1)
        ii = i.astype(jnp.int32)
        n = flat.shape[0]
        if mode == "wrap":
            ii = ((ii % n) + n) % n
        elif mode == "clip":
            ii = jnp.clip(ii, 0, n - 1)
        else:  # 'raise' semantics can't raise under XLA; negative wrap only
            ii = jnp.where(ii < 0, ii + n, ii)
        return jnp.take(flat, ii, axis=0)

    return apply(f, [x, index], name="take")


def index_fill(x, index, axis, value, name=None):
    x, index = coerce(x), coerce(index)

    def f(a, i):
        moved = jnp.moveaxis(a, axis, 0)
        filled = moved.at[i.astype(jnp.int32)].set(jnp.asarray(value, a.dtype))
        return jnp.moveaxis(filled, 0, axis)

    return apply(f, [x, index], name="index_fill")


def index_fill_(x, index, axis, value, name=None):
    from .dispatch import inplace_rebind

    return inplace_rebind(x, index_fill(x, index, axis, value))


def unflatten(x, axis, shape, name=None):
    x = coerce(x)

    def f(a):
        ax = axis % a.ndim
        new = list(a.shape[:ax]) + list(shape) + list(a.shape[ax + 1 :])
        # one -1 allowed (inferred)
        if -1 in shape:
            known = 1
            for s in shape:
                if s != -1:
                    known *= s
            new[new.index(-1)] = a.shape[ax] // known
        return a.reshape(new)

    return apply(f, [x], name="unflatten")


def unfold(x, axis, size, step, name=None):
    """Sliding windows along `axis` (reference: paddle.unfold / Tensor.unfold):
    output gains a trailing window dim of length `size`."""
    x = coerce(x)

    def f(a):
        ax = axis % a.ndim
        length = a.shape[ax]
        n_win = (length - size) // step + 1
        starts = jnp.arange(n_win) * step
        idx = starts[:, None] + jnp.arange(size)[None, :]  # [n_win, size]
        moved = jnp.moveaxis(a, ax, 0)  # [L, ...]
        wins = moved[idx]  # [n_win, size, ...]
        wins = jnp.moveaxis(wins, 1, -1)  # [n_win, ..., size]
        return jnp.moveaxis(wins, 0, ax)

    return apply(f, [x], name="unfold")


def shard_index(x, index_num, nshards, shard_id, ignore_value=-1, name=None):
    """Relabel global ids to shard-local ids (reference: paddle.shard_index)."""
    x = coerce(x)
    shard_size = (index_num + nshards - 1) // nshards

    def f(a):
        lo = shard_id * shard_size
        hi = lo + shard_size
        inside = (a >= lo) & (a < hi)
        return jnp.where(inside, a - lo, ignore_value).astype(a.dtype)

    return apply(f, [x], name="shard_index")


# -- round-5 long tail (reference python/paddle/tensor/manipulation.py) -----
def hstack(x, name=None):
    return apply(lambda *a: jnp.hstack(a), [coerce(t) for t in x], name="hstack")


def vstack(x, name=None):
    return apply(lambda *a: jnp.vstack(a), [coerce(t) for t in x], name="vstack")


def dstack(x, name=None):
    return apply(lambda *a: jnp.dstack(a), [coerce(t) for t in x], name="dstack")


def column_stack(x, name=None):
    return apply(lambda *a: jnp.column_stack(a), [coerce(t) for t in x], name="column_stack")


def fliplr(x, name=None):
    return apply(lambda a: jnp.fliplr(a), [coerce(x)], name="fliplr")


def flipud(x, name=None):
    return apply(lambda a: jnp.flipud(a), [coerce(x)], name="flipud")


def ravel(x, name=None):
    return apply(lambda a: a.ravel(), [coerce(x)], name="ravel")


def msort(x, name=None):
    return apply(lambda a: jnp.sort(a, axis=0), [coerce(x)], name="msort")


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors (reference: paddle.cartesian_prod)."""
    ins = [coerce(t) for t in x]

    def f(*arrs):
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.ravel() for g in grids], axis=-1)

    return apply(f, ins, name="cartesian_prod")


def combinations(x, r=2, with_replacement=False, name=None):
    """r-length combinations of a 1-D tensor (reference:
    paddle.combinations).  Index set is computed host-side (static shape)."""
    import itertools

    import numpy as _np

    if r < 1:
        raise ValueError(f"combinations: r must be >= 1, got {r}")
    x = coerce(x)
    n = x.shape[0]
    it = (
        itertools.combinations_with_replacement(range(n), r)
        if with_replacement
        else itertools.combinations(range(n), r)
    )
    idx = _np.array(list(it), _np.int32).reshape(-1, r)
    return apply(lambda a: a[jnp.asarray(idx)], [x], name="combinations")


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """Embed `value` into x at the sliced region (reference:
    paddle.slice_scatter)."""
    x, value = coerce(x), coerce(value)

    import builtins

    def f(a, v):
        # NB: this module defines paddle.slice, shadowing the builtin
        sl = [builtins.slice(None)] * a.ndim
        for ax, st, en, sr in zip(axes, starts, ends, strides):
            sl[ax] = builtins.slice(st, en, sr)
        return a.at[tuple(sl)].set(v.astype(a.dtype))

    return apply(f, [x, value], name="slice_scatter")


def select_scatter(x, value, axis, index, name=None):
    """Embed `value` at position `index` along `axis` (reference:
    paddle.select_scatter)."""
    x, value = coerce(x), coerce(value)

    import builtins

    def f(a, v):
        sl = [builtins.slice(None)] * a.ndim
        sl[axis] = index
        return a.at[tuple(sl)].set(v.astype(a.dtype))

    return apply(f, [x, value], name="select_scatter")


def masked_scatter(x, mask, value, name=None):
    """Fill masked positions from `value`'s leading elements (reference:
    paddle.masked_scatter).  `value` must supply at least mask.sum()
    elements — checked eagerly (data-dependent, so unverifiable under
    @to_static tracing, where an undersized value repeats its last
    element)."""
    import jax as _jax

    x, mask, value = coerce(x), coerce(mask), coerce(value)
    if not isinstance(mask._data, _jax.core.Tracer):
        import numpy as _np

        needed = int(_np.asarray(jnp.broadcast_to(mask._data, x._data.shape).sum()))
        if value.size < needed:
            raise ValueError(
                f"masked_scatter: value has {value.size} elements but mask "
                f"selects {needed}"
            )

    def f(a, m, v):
        mb = jnp.broadcast_to(m, a.shape).astype(bool)
        # k-th True position takes v.ravel()[k] (the reference contract)
        order = jnp.cumsum(mb.ravel()) - 1
        gathered = v.ravel()[jnp.clip(order, 0, v.size - 1)].reshape(a.shape)
        return jnp.where(mb, gathered.astype(a.dtype), a)

    return apply(f, [x, mask, value], name="masked_scatter")
