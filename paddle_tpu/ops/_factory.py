"""Compact op-definition factories (the framework's analogue of the
reference's ops.yaml codegen — SURVEY.md §2.1 'Op YAML + codegen': one table
stamps out the Python API, autograd recording, and XLA lowering at once)."""

from __future__ import annotations

import jax.numpy as jnp

from .dispatch import apply, coerce
from ..tensor import Tensor


def _is_scalar(x):
    return isinstance(x, (bool, int, float, complex))


def unary_op(name, fn):
    def op(x, name=None):
        x = coerce(x)
        return apply(fn, [x], name=name or op_name)

    op_name = name
    op.__name__ = name
    op.__qualname__ = name
    return op


def binary_op(name, fn, reverse=False):
    def op(x, y, name=None):
        if _is_scalar(y) and isinstance(x, Tensor):
            return apply(lambda a: fn(a, y), [x], name=op_name)
        if _is_scalar(x) and isinstance(y, Tensor):
            return apply(lambda b: fn(x, b), [y], name=op_name)
        x, y = coerce(x), coerce(y)
        return apply(fn, [x, y], name=op_name)

    op_name = name
    op.__name__ = name
    op.__qualname__ = name
    return op


def inplace_variant(op):
    from .dispatch import inplace_rebind

    def op_(x, *args, **kwargs):
        return inplace_rebind(x, op(x, *args, **kwargs))

    op_.__name__ = op.__name__ + "_"
    return op_


def reduce_op(name, fn):
    """fn(a, axis, keepdims) -> array."""

    def op(x, axis=None, keepdim=False, name=None):
        x = coerce(x)
        if isinstance(axis, (list, tuple)):
            axis = tuple(int(a) for a in axis)
        elif axis is not None and not isinstance(axis, int):
            axis = int(axis)
        return apply(lambda a: fn(a, axis, keepdim), [x], name=op_name)

    op_name = name
    op.__name__ = name
    return op
