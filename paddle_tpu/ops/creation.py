"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..framework import core as _core
from ..tensor import Tensor
from .dispatch import apply, coerce, wrap


def _dt(dtype, default=None):
    if dtype is None:
        dtype = default or _core.get_default_dtype()
    return _core.to_jax_dtype(_core.convert_dtype(dtype))


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._data) if isinstance(s, Tensor) else int(s) for s in shape]


def zeros(shape, dtype=None, name=None):
    return wrap(jnp.zeros(_shape_list(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return wrap(jnp.ones(_shape_list(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = (
            "bool"
            if isinstance(fill_value, bool)
            else "int64"
            if isinstance(fill_value, int)
            else _core.get_default_dtype()
        )
    return wrap(jnp.full(_shape_list(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.zeros_like(a, dtype=_dt(dtype, x.dtype)), [x.detach()])


def ones_like(x, dtype=None, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.ones_like(a, dtype=_dt(dtype, x.dtype)), [x.detach()])


def full_like(x, fill_value, dtype=None, name=None):
    x = coerce(x)
    return apply(
        lambda a: jnp.full_like(a, fill_value, dtype=_dt(dtype, x.dtype)), [x.detach()]
    )


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v

    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (
            "int64"
            if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
            else _core.get_default_dtype()
        )
    return wrap(jnp.arange(start, end, step, _dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v

    return wrap(jnp.linspace(val(start), val(stop), int(val(num)), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v

    return wrap(
        jnp.logspace(val(start), val(stop), int(val(num)), base=val(base), dtype=_dt(dtype))
    )


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return wrap(jnp.eye(int(num_rows), num_columns and int(num_columns), dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    x = coerce(x)

    def f(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a, dtype=bool), k=offset)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diag(a, k=offset)

    return apply(f, [x], name="diag")


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    x = coerce(x)

    def f(a):
        out = jnp.zeros(a.shape + (a.shape[-1] + abs(offset),), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out[..., : a.shape[-1] + abs(offset)]
        base = jnp.zeros(a.shape[:-1] + (a.shape[-1] + abs(offset), a.shape[-1] + abs(offset)), a.dtype)
        base = base.at[..., r, c].set(a)
        return jnp.moveaxis(jnp.moveaxis(base, -2, dim1), -1, dim2) if (dim1, dim2) != (-2, -1) else base

    return apply(f, [x], name="diag_embed")


def diagflat(x, offset=0, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.diagflat(a, k=offset), [x], name="diagflat")


def tril(x, diagonal=0, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.tril(a, k=diagonal), [x], name="tril")


def triu(x, diagonal=0, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.triu(a, k=diagonal), [x], name="triu")


def meshgrid(*args, name=None):
    args = [coerce(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return list(apply(lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), args, multi=True))


def assign(x, output=None, name=None):
    x = coerce(x)
    out = apply(lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.inexact) else jnp.array(a), [x], name="assign")
    if output is not None:
        from .dispatch import inplace_rebind

        return inplace_rebind(output, out)
    return out


def clone(x, name=None):
    return assign(x)


def tolist(x):
    return coerce(x).tolist()


def numel(x, name=None):
    return wrap(jnp.asarray(coerce(x).size, jnp.int64))


def is_tensor(x):
    return isinstance(x, Tensor)


def complex(real, imag, name=None):
    real, imag = coerce(real), coerce(imag)
    return apply(lambda r, i: r + 1j * i, [real, imag], name="complex")


def as_complex(x, name=None):
    x = coerce(x)
    return apply(lambda a: a[..., 0] + 1j * a[..., 1], [x], name="as_complex")


def as_real(x, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.stack([a.real, a.imag], -1), [x], name="as_real")


def tril_indices(row, col=None, offset=0, dtype="int64", name=None):
    """[2, n] lower-triangle indices (reference: paddle.tril_indices)."""
    import jax.numpy as jnp

    from ..framework import core as _core
    from .dispatch import wrap

    col = row if col is None else col
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return wrap(jnp.stack([r, c]).astype(_core.to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    import jax.numpy as jnp

    from ..framework import core as _core
    from .dispatch import wrap

    col = row if col is None else col
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return wrap(jnp.stack([r, c]).astype(_core.to_jax_dtype(dtype)))
