"""Math ops (reference surface: python/paddle/tensor/math.py over PHI kernels;
here each op is a direct XLA lowering via jnp/lax — SURVEY.md §2.3)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework import core as _core
from ..tensor import Tensor
from ._factory import binary_op, inplace_variant, unary_op, _is_scalar
from .dispatch import apply, coerce, amp_cast_inputs, inplace_rebind

# -- binary -----------------------------------------------------------------
add = binary_op("add", jnp.add)
subtract = binary_op("subtract", jnp.subtract)
multiply = binary_op("multiply", jnp.multiply)
divide = binary_op("divide", jnp.divide)
floor_divide = binary_op("floor_divide", jnp.floor_divide)
remainder = binary_op("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
pow = binary_op("pow", jnp.power)
maximum = binary_op("maximum", jnp.maximum)
minimum = binary_op("minimum", jnp.minimum)
fmax = binary_op("fmax", jnp.fmax)
fmin = binary_op("fmin", jnp.fmin)
atan2 = binary_op("atan2", jnp.arctan2)
hypot = binary_op("hypot", jnp.hypot)
logaddexp = binary_op("logaddexp", jnp.logaddexp)
heaviside = binary_op("heaviside", jnp.heaviside)
copysign = binary_op("copysign", jnp.copysign)
nextafter = binary_op("nextafter", jnp.nextafter)
gcd = binary_op("gcd", jnp.gcd)
lcm = binary_op("lcm", jnp.lcm)

add_ = inplace_variant(add)
subtract_ = inplace_variant(subtract)
multiply_ = inplace_variant(multiply)
divide_ = inplace_variant(divide)
remainder_ = inplace_variant(remainder)
floor_divide_ = inplace_variant(floor_divide)
pow_ = inplace_variant(pow)

# -- unary ------------------------------------------------------------------
exp = unary_op("exp", jnp.exp)
expm1 = unary_op("expm1", jnp.expm1)
log = unary_op("log", jnp.log)
log2 = unary_op("log2", jnp.log2)
log10 = unary_op("log10", jnp.log10)
log1p = unary_op("log1p", jnp.log1p)
sqrt = unary_op("sqrt", jnp.sqrt)
rsqrt = unary_op("rsqrt", lax.rsqrt)
square = unary_op("square", jnp.square)
sin = unary_op("sin", jnp.sin)
cos = unary_op("cos", jnp.cos)
tan = unary_op("tan", jnp.tan)
asin = unary_op("asin", jnp.arcsin)
acos = unary_op("acos", jnp.arccos)
atan = unary_op("atan", jnp.arctan)
sinh = unary_op("sinh", jnp.sinh)
cosh = unary_op("cosh", jnp.cosh)
tanh = unary_op("tanh", jnp.tanh)
asinh = unary_op("asinh", jnp.arcsinh)
acosh = unary_op("acosh", jnp.arccosh)
atanh = unary_op("atanh", jnp.arctanh)
abs = unary_op("abs", jnp.abs)
neg = unary_op("neg", jnp.negative)
reciprocal = unary_op("reciprocal", jnp.reciprocal)
floor = unary_op("floor", jnp.floor)
ceil = unary_op("ceil", jnp.ceil)
round = unary_op("round", jnp.round)
trunc = unary_op("trunc", jnp.trunc)
frac = unary_op("frac", lambda a: a - jnp.trunc(a))
sign = unary_op("sign", jnp.sign)
erf = unary_op("erf", jax.scipy.special.erf)
erfinv = unary_op("erfinv", jax.scipy.special.erfinv)
lgamma = unary_op("lgamma", jax.scipy.special.gammaln)
digamma = unary_op("digamma", jax.scipy.special.digamma)
i0 = unary_op("i0", jax.scipy.special.i0)
i1 = unary_op("i1", jax.scipy.special.i1)
sigmoid = unary_op("sigmoid", jax.nn.sigmoid)
logit = unary_op("logit", jax.scipy.special.logit)
angle = unary_op("angle", jnp.angle)
conj = unary_op("conj", jnp.conj)
real = unary_op("real", jnp.real)
imag = unary_op("imag", jnp.imag)
rad2deg = unary_op("rad2deg", jnp.rad2deg)
deg2rad = unary_op("deg2rad", jnp.deg2rad)

exp_ = inplace_variant(exp)
sqrt_ = inplace_variant(sqrt)
rsqrt_ = inplace_variant(rsqrt)
reciprocal_ = inplace_variant(reciprocal)
floor_ = inplace_variant(floor)
ceil_ = inplace_variant(ceil)
round_ = inplace_variant(round)
tanh_ = inplace_variant(tanh)
abs_ = inplace_variant(abs)
neg_ = inplace_variant(neg)


# -- scale / clip / lerp ----------------------------------------------------
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = coerce(x)
    s = scale._data if isinstance(scale, Tensor) else scale
    if bias_after_scale:
        out = apply(lambda a: a * s + bias, [x], name="scale")
    else:
        out = apply(lambda a: (a + bias) * s, [x], name="scale")
    return out


scale_ = inplace_variant(scale)


def clip(x, min=None, max=None, name=None):
    x = coerce(x)
    lo = min._data if isinstance(min, Tensor) else min
    hi = max._data if isinstance(max, Tensor) else max
    return apply(lambda a: jnp.clip(a, lo, hi), [x], name="clip")


clip_ = inplace_variant(clip)


def lerp(x, y, weight, name=None):
    x, y = coerce(x), coerce(y)
    if isinstance(weight, Tensor):
        return apply(lambda a, b, w: a + w * (b - a), [x, y, weight], name="lerp")
    return apply(lambda a, b: a + weight * (b - a), [x, y], name="lerp")


lerp_ = inplace_variant(lerp)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    x = coerce(x)
    return apply(lambda a: scale_b * jnp.tanh(scale_a * a), [x], name="stanh")


def multiplex(inputs, index, name=None):
    inputs = [coerce(i) for i in inputs]
    index = coerce(index)
    return apply(
        lambda idx, *xs: jnp.stack(xs, 0)[idx.reshape(-1), jnp.arange(xs[0].shape[0])],
        [index] + inputs,
        name="multiplex",
    )


def increment(x, value=1.0, name=None):
    return inplace_rebind(x, apply(lambda a: a + value, [x], name="increment"))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), [x])


# -- matmul family ----------------------------------------------------------
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = coerce(x), coerce(y)
    x, y = amp_cast_inputs([x, y], "white")

    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)

    return apply(f, [x, y], name="matmul")


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    x, y = coerce(x), coerce(y)
    return apply(lambda a, b: (a * b).sum(-1), [x, y], name="dot")


def mv(x, vec, name=None):
    return matmul(x, vec)


def outer(x, y, name=None):
    x, y = coerce(x), coerce(y)
    return apply(lambda a, b: jnp.outer(a, b), [x, y], name="outer")


def inner(x, y, name=None):
    x, y = coerce(x), coerce(y)
    return apply(jnp.inner, [x, y], name="inner")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    input, x, y = coerce(input), coerce(x), coerce(y)
    return apply(
        lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), [input, x, y], name="addmm"
    )


def kron(x, y, name=None):
    x, y = coerce(x), coerce(y)
    return apply(jnp.kron, [x, y], name="kron")


def cross(x, y, axis=9, name=None):
    x, y = coerce(x), coerce(y)
    ax = axis if axis != 9 else None
    if ax is None:
        # paddle default: first axis with dim 3
        ax = next(i for i, d in enumerate(x.shape) if d == 3)
    return apply(lambda a, b: jnp.cross(a, b, axis=ax), [x, y], name="cross")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = coerce(x)
    ins = [x]
    pre_i = app_i = None
    if prepend is not None:
        prepend = coerce(prepend)
        ins.append(prepend)
        pre_i = len(ins) - 1
    if append is not None:
        append = coerce(append)
        ins.append(append)
        app_i = len(ins) - 1

    def f(*arrs):
        return jnp.diff(
            arrs[0],
            n=n,
            axis=axis,
            prepend=arrs[pre_i] if pre_i is not None else None,
            append=arrs[app_i] if app_i is not None else None,
        )

    return apply(f, ins, name="diff")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.trace(a, offset, axis1, axis2), [x], name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.diagonal(a, offset, axis1, axis2), [x], name="diagonal")


# -- logic / comparison (non-differentiable outputs) ------------------------
equal = binary_op("equal", jnp.equal)
not_equal = binary_op("not_equal", jnp.not_equal)
greater_than = binary_op("greater_than", jnp.greater)
greater_equal = binary_op("greater_equal", jnp.greater_equal)
less_than = binary_op("less_than", jnp.less)
less_equal = binary_op("less_equal", jnp.less_equal)
logical_and = binary_op("logical_and", jnp.logical_and)
logical_or = binary_op("logical_or", jnp.logical_or)
logical_xor = binary_op("logical_xor", jnp.logical_xor)
logical_not = unary_op("logical_not", jnp.logical_not)
bitwise_and = binary_op("bitwise_and", jnp.bitwise_and)
bitwise_or = binary_op("bitwise_or", jnp.bitwise_or)
bitwise_xor = binary_op("bitwise_xor", jnp.bitwise_xor)
bitwise_not = unary_op("bitwise_not", jnp.bitwise_not)
isnan = unary_op("isnan", jnp.isnan)
isinf = unary_op("isinf", jnp.isinf)
isfinite = unary_op("isfinite", jnp.isfinite)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = coerce(x), coerce(y)
    return apply(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        [x, y],
        name="isclose",
    )


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = coerce(x), coerce(y)
    return apply(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        [x, y],
        name="allclose",
    )


def equal_all(x, y, name=None):
    x, y = coerce(x), coerce(y)
    return apply(lambda a, b: jnp.array_equal(a, b), [x, y], name="equal_all")


# ---------------------------------------------------------------------------
# long-tail math ops (round 4: §2.3 API-breadth pass)
# ---------------------------------------------------------------------------


def add_n(inputs, name=None):
    """Sum a list of tensors (reference: paddle.add_n)."""
    ts = [coerce(t) for t in inputs]

    def f(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out

    return apply(f, ts, name="add_n")


def ldexp(x, y, name=None):
    x, y = coerce(x), coerce(y)
    return apply(lambda a, b: (a * jnp.exp2(b.astype(jnp.float32))).astype(jnp.result_type(a, jnp.float32)), [x, y], name="ldexp")


def logcumsumexp(x, axis=None, name=None):
    x = coerce(x)

    def f(a):
        ax = axis
        if ax is None:
            a = a.reshape(-1)
            ax = 0
        # logaddexp is associative: a numerically-stable parallel scan
        return jax.lax.associative_scan(jnp.logaddexp, a, axis=ax)

    return apply(f, [x], name="logcumsumexp")


def sinc(x, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.sinc(a), [x], name="sinc")


def signbit(x, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.signbit(a), [x], name="signbit")


def sgn(x, name=None):
    """sign for real; unit complex phase for complex (reference: paddle.sgn)."""
    x = coerce(x)

    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.where(mag == 0, 1, mag))
        return jnp.sign(a)

    return apply(f, [x], name="sgn")


def polar(abs, angle, name=None):
    abs, angle = coerce(abs), coerce(angle)
    return apply(lambda r, t: (r * jnp.cos(t) + 1j * r * jnp.sin(t)).astype(jnp.complex64), [abs, angle], name="polar")


def polygamma(x, n, name=None):
    x = coerce(x)
    from jax.scipy.special import polygamma as _pg

    return apply(lambda a: _pg(int(n), a), [x], name="polygamma")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    x = coerce(x)
    return apply(
        lambda a: jnp.nanquantile(a.astype(jnp.float32), q, axis=axis, keepdims=keepdim),
        [x],
        name="nanquantile",
    )


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    """Pairwise p-norm distance [.., M, D] x [.., N, D] -> [.., M, N]."""
    x, y = coerce(x), coerce(y)

    def f(a, b):
        if p == 2.0:
            # matmul form rides the MXU: |a-b|^2 = |a|^2 + |b|^2 - 2ab
            a2 = jnp.sum(a * a, -1)[..., :, None]
            b2 = jnp.sum(b * b, -1)[..., None, :]
            ab = jnp.matmul(a, jnp.swapaxes(b, -1, -2))
            return jnp.sqrt(jnp.maximum(a2 + b2 - 2 * ab, 0.0))
        d = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        return jnp.sum(d**p, -1) ** (1.0 / p)

    return apply(f, [x, y], name="cdist")


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of [N, D] (upper triangle, row-major)."""
    x = coerce(x)

    def f(a):
        n = a.shape[0]
        full = jnp.abs(a[:, None, :] - a[None, :, :])
        d = jnp.sum(full**p, -1) ** (1.0 / p)
        iu = jnp.triu_indices(n, k=1)
        return d[iu]

    return apply(f, [x], name="pdist")


def renorm(x, p, axis, max_norm, name=None):
    x = coerce(x)

    def f(a):
        ax = axis % a.ndim
        other = tuple(i for i in range(a.ndim) if i != ax)
        norms = jnp.sum(jnp.abs(a) ** p, axis=other, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return a * factor.astype(a.dtype)

    return apply(f, [x], name="renorm")


def vander(x, n=None, increasing=False, name=None):
    x = coerce(x)
    cols = n if n is not None else x.shape[0]
    return apply(lambda a: jnp.vander(a, N=cols, increasing=increasing), [x], name="vander")


def is_complex(x):
    return jnp.issubdtype(coerce(x)._raw.dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(coerce(x)._raw.dtype, jnp.floating)


def is_empty(x, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.asarray(a.size == 0), [x], name="is_empty")


def rank(x, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.asarray(a.ndim, jnp.int32), [x], name="rank")


def tensordot(x, y, axes=2, name=None):
    x, y = coerce(x), coerce(y)
    ax = axes
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in ax)
    return apply(lambda a, b: jnp.tensordot(a, b, axes=ax), [x, y], name="tensordot")


# -- round-5 long tail (reference python/paddle/tensor/math.py) -------------
i0e = unary_op("i0e", jax.scipy.special.i0e)
i1e = unary_op("i1e", jax.scipy.special.i1e)
gammaln = unary_op("gammaln", jax.scipy.special.gammaln)
positive = unary_op("positive", lambda a: a)
isneginf = unary_op("isneginf", jnp.isneginf)
isposinf = unary_op("isposinf", jnp.isposinf)
isreal = unary_op("isreal", jnp.isreal)


def multigammaln(x, p, name=None):
    """log multivariate gamma (reference: paddle.multigammaln)."""
    x = coerce(x)
    p = int(p)

    def f(a):
        a32 = a.astype(jnp.float32) if a.dtype not in (jnp.float32, jnp.float64) else a
        out = 0.25 * p * (p - 1) * jnp.log(jnp.asarray(jnp.pi, a32.dtype))
        for i in range(p):
            out = out + jax.scipy.special.gammaln(a32 - 0.5 * i)
        # preserve inexact input dtypes (bf16/f16 included); ints -> f32
        if jnp.issubdtype(a.dtype, jnp.inexact):
            return out.astype(a.dtype)
        return out

    return apply(f, [x], name="multigammaln")


def frexp(x, name=None):
    """Decompose into (mantissa, exponent) with 0.5 <= |m| < 1 (reference:
    paddle.frexp)."""
    x = coerce(x)

    def f(a):
        m, e = jnp.frexp(a)
        return m, e.astype(jnp.int32)

    return apply(f, [x], multi=True, name="frexp")


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    x, t = coerce(x), coerce(test_x)
    return apply(
        lambda a, b: jnp.isin(a, b, assume_unique=assume_unique, invert=invert),
        [x, t],
        name="isin",
    )


def vdot(x, y, name=None):
    """Flattened conjugating dot product (reference: paddle.vdot)."""
    x, y = coerce(x), coerce(y)
    return apply(lambda a, b: jnp.vdot(a, b), [x, y], name="vdot")


def cauchy_(x, loc=0, scale=1, name=None):
    """Fill in place with Cauchy samples (reference: Tensor.cauchy_)."""
    from .random import _key

    x = coerce(x)
    key = _key()

    def f(a):
        return loc + scale * jax.random.cauchy(key, a.shape, jnp.float32).astype(a.dtype)

    return inplace_rebind(x, apply(f, [x], name="cauchy_"))


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal integration (reference: paddle.trapezoid)."""
    if x is not None and dx is not None:
        raise ValueError("trapezoid: pass either x or dx, not both")
    y = coerce(y)
    ins = [y] + ([coerce(x)] if x is not None else [])
    d = 1.0 if dx is None else dx

    def f(a, *rest):
        if rest:
            return jnp.trapezoid(a, rest[0], axis=axis)
        return jnp.trapezoid(a, dx=d, axis=axis)

    return apply(f, ins, name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Cumulative trapezoidal integration (reference:
    paddle.cumulative_trapezoid)."""
    if x is not None and dx is not None:
        raise ValueError("cumulative_trapezoid: pass either x or dx, not both")
    y = coerce(y)
    ins = [y] + ([coerce(x)] if x is not None else [])
    d = 1.0 if dx is None else dx

    def f(a, *rest):
        a = jnp.moveaxis(a, axis, -1)
        if rest:
            xs = rest[0]
            if xs.ndim > 1:
                xs = jnp.moveaxis(xs, axis, -1)
            xs = jnp.broadcast_to(xs, a.shape)
            widths = xs[..., 1:] - xs[..., :-1]
        else:
            widths = d
        areas = (a[..., 1:] + a[..., :-1]) / 2.0 * widths
        return jnp.moveaxis(jnp.cumsum(areas, -1), -1, axis)

    return apply(f, ins, name="cumulative_trapezoid")


def nanargmax(x, axis=None, keepdim=False, name=None):
    x = coerce(x)
    return apply(
        lambda a: jnp.nanargmax(a, axis=axis, keepdims=keepdim),
        [x], name="nanargmax",
    )


def nanargmin(x, axis=None, keepdim=False, name=None):
    x = coerce(x)
    return apply(
        lambda a: jnp.nanargmin(a, axis=axis, keepdims=keepdim),
        [x], name="nanargmin",
    )


def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) batched (reference: paddle.baddbmm)."""
    input, x, y = coerce(input), coerce(x), coerce(y)
    return apply(
        lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
        [input, x, y], name="baddbmm",
    )


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    x = coerce(x)

    def f(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        return jnp.linspace(lo, hi, bins + 1).astype(jnp.float32)

    return apply(f, [x], name="histogram_bin_edges")
