"""Linear algebra ops (reference: python/paddle/tensor/linalg.py → PHI
lapack/cublas kernels; here XLA's native linalg lowerings)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .dispatch import apply, coerce


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = coerce(x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis

    def f(a):
        if p is None or p == "fro":
            if ax is None:
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            return jnp.linalg.norm(a, ord=None, axis=ax, keepdims=keepdim)
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return apply(f, [x], name="norm")


def dist(x, y, p=2, name=None):
    x, y = coerce(x), coerce(y)

    def f(a, b):
        d = a - b
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)

    return apply(f, [x, y], name="dist")


def cholesky(x, upper=False, name=None):
    x = coerce(x)

    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return apply(f, [x], name="cholesky")


def qr(x, mode="reduced", name=None):
    x = coerce(x)
    q, r = apply(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), [x], multi=True, name="qr")
    return q, r


def svd(x, full_matrices=False, name=None):
    x = coerce(x)
    u, s, vh = apply(
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
        [x],
        multi=True,
        name="svd",
    )
    return u, s, vh


def inverse(x, name=None):
    x = coerce(x)
    return apply(jnp.linalg.inv, [x], name="inverse")


inv = inverse


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.linalg.pinv(a, rcond=rcond, hermitian=hermitian), [x])


def solve(x, y, name=None):
    x, y = coerce(x), coerce(y)
    return apply(jnp.linalg.solve, [x, y], name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    x, y = coerce(x), coerce(y)
    import jax

    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return apply(f, [x, y], name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = coerce(x), coerce(y)
    sol, res, rank, sv = apply(
        lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)), [x, y], multi=True
    )
    return sol, res, rank, sv


def matrix_power(x, n, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.linalg.matrix_power(a, n), [x], name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.linalg.matrix_rank(a, tol=tol), [x], name="matrix_rank")


def slogdet(x, name=None):
    x = coerce(x)
    s, l = apply(lambda a: tuple(jnp.linalg.slogdet(a)), [x], multi=True, name="slogdet")
    return s, l


def det(x, name=None):
    x = coerce(x)
    return apply(jnp.linalg.det, [x], name="det")


def eig(x, name=None):
    x = coerce(x)
    import numpy as np

    w, v = np.linalg.eig(np.asarray(x._data))
    from .dispatch import wrap

    return wrap(jnp.asarray(w)), wrap(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    x = coerce(x)
    w, v = apply(lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), [x], multi=True)
    return w, v


def eigvals(x, name=None):
    w, _ = eig(x)
    return w


def eigvalsh(x, UPLO="L", name=None):
    x = coerce(x)
    return apply(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), [x], name="eigvalsh")


def multi_dot(x, name=None):
    xs = [coerce(v) for v in x]
    return apply(lambda *arrs: jnp.linalg.multi_dot(arrs), xs, name="multi_dot")


def cond(x, p=None, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.linalg.cond(a, p=p), [x], name="cond")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    x = coerce(x)
    return apply(
        lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), [x], name="cov"
    )


def corrcoef(x, rowvar=True, name=None):
    x = coerce(x)
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), [x], name="corrcoef")


def householder_product(x, tau, name=None):
    x, tau = coerce(x), coerce(tau)

    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(n):
            v = jnp.concatenate([jnp.zeros(i, a.dtype), jnp.ones(1, a.dtype), a[i + 1 :, i]])
            q = q - t[i] * (q @ v)[:, None] * v[None, :]
        return q[:, :n]

    return apply(f, [x, tau], name="householder_product")


def einsum(equation, *operands):
    ops_ = [coerce(o) for o in operands]
    return apply(lambda *arrs: jnp.einsum(equation, *arrs), ops_, name="einsum")


# -- round-5 long tail (reference python/paddle/tensor/linalg.py) -----------
def cholesky_solve(x, y, upper=False, name=None):
    """Solve A X = B given the Cholesky factor `y` of A (reference:
    paddle.linalg.cholesky_solve)."""
    x, y = coerce(x), coerce(y)

    def f(b, L):
        import jax.scipy.linalg as jsl

        return jsl.cho_solve((L, not upper), b)

    return apply(f, [x, y], name="cholesky_solve")


def lu(x, pivot=True, get_infos=False, name=None):
    """LU factorization (reference: paddle.linalg.lu): returns (LU packed,
    pivots 1-indexed[, info])."""
    x = coerce(x)

    def f(a):
        import jax.scipy.linalg as jsl

        lu_, piv = jsl.lu_factor(a)
        return lu_, (piv + 1).astype(jnp.int32)

    lu_, piv = apply(f, [x], multi=True, name="lu")
    if get_infos:
        from .creation import zeros

        return lu_, piv, zeros([1], dtype="int32")
    return lu_, piv


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack paddle.linalg.lu output into (P, L, U)."""
    x, y = coerce(x), coerce(y)

    def f(lu_, piv):
        m, n = lu_.shape[-2], lu_.shape[-1]
        k = min(m, n)
        # paddle shapes: P (m, m), L (m, k), U (k, n)
        L = jnp.tril(lu_[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_.dtype)
        U = jnp.triu(lu_[..., :k, :])

        def perm_one(pv):
            perm = jnp.arange(m)
            for i in range(pv.shape[-1]):
                j = pv[i] - 1
                pi, pj = perm[i], perm[j]
                perm = perm.at[i].set(pj).at[j].set(pi)
            return perm

        if piv.ndim == 1:
            P = jnp.eye(m, dtype=lu_.dtype)[perm_one(piv)].T
        else:
            pflat = piv.reshape(-1, piv.shape[-1])
            perms = jax.vmap(perm_one)(pflat)
            P = (
                jnp.eye(m, dtype=lu_.dtype)[perms]
                .swapaxes(-1, -2)
                .reshape(piv.shape[:-1] + (m, m))
            )
        return P, L, U

    return apply(f, [x, y], multi=True, name="lu_unpack")


def matrix_exp(x, name=None):
    x = coerce(x)

    def f(a):
        import jax.scipy.linalg as jsl

        return jsl.expm(a)

    return apply(f, [x], name="matrix_exp")


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply y by the orthogonal Q from a QR given (householder vectors,
    tau) (reference: paddle.linalg.ormqr)."""
    x, tau, y = coerce(x), coerce(tau), coerce(y)

    def f(a, t, other):
        m = a.shape[-2]
        # build the FULL m x m Q (LAPACK ormqr semantics): pad the reflector
        # panel to square with zero columns and tau with zeros (identity
        # reflectors)
        pad_cols = m - a.shape[-1]
        if pad_cols > 0:
            a = jnp.concatenate([a, jnp.zeros(a.shape[:-1] + (pad_cols,), a.dtype)], -1)
            t = jnp.concatenate([t, jnp.zeros(t.shape[:-1] + (pad_cols,), t.dtype)], -1)
        Q = jax.lax.linalg.householder_product(a, t)
        Qm = jnp.swapaxes(Q, -1, -2) if transpose else Q
        return Qm @ other if left else other @ Qm

    return apply(f, [x, tau, y], name="ormqr")


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (reference: paddle.linalg.svd_lowrank;
    Halko et al. randomized range finder with `niter` power iterations)."""
    from ..framework.random import default_generator

    x = coerce(x)
    key = default_generator.next_key()
    ins = [x] + ([coerce(M)] if M is not None else [])

    def f(a, *rest):
        A = a - rest[0] if rest else a
        m, n = A.shape[-2], A.shape[-1]
        r = min(q, m, n)
        G = jax.random.normal(key, A.shape[:-2] + (n, r), A.dtype)
        Y = A @ G
        for _ in range(niter):
            Y = A @ (A.swapaxes(-1, -2) @ Y)
        Q, _ = jnp.linalg.qr(Y)
        B = Q.swapaxes(-1, -2) @ A
        u, s, vh = jnp.linalg.svd(B, full_matrices=False)
        return Q @ u, s, vh.swapaxes(-1, -2)

    return apply(f, ins, multi=True, name="svd_lowrank")
