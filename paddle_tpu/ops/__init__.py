"""Op namespace + Tensor method patching.

Mirrors the reference's pattern of monkey-patching generated op functions
onto the eager Tensor (paddle/fluid/pybind/eager_op_function* +
python/paddle/tensor/__init__.py tensor_method_func list — SURVEY.md §2.3).
"""

from __future__ import annotations

import builtins

import numpy as np
import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .dispatch import apply, coerce, wrap, inplace_rebind

from .math import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403

from . import math as _math
from . import creation as _creation
from . import manipulation as _manipulation
from . import reduction as _reduction
from . import search as _search
from . import random as _random
from . import linalg as _linalg


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------


def _prep_index(key):
    """Normalize a python index; returns (static_key_builder, tensor_indices)."""
    if not isinstance(key, tuple):
        key = (key,)
    tensors = []
    spec = []
    for k in key:
        if isinstance(k, Tensor):
            spec.append(("t", len(tensors), k.dtype == "bool"))
            tensors.append(k)
        elif isinstance(k, np.ndarray):
            spec.append(("a", jnp.asarray(k), k.dtype == np.bool_))
        elif isinstance(k, (list,)):
            arr = np.asarray(k)
            spec.append(("a", jnp.asarray(arr), arr.dtype == np.bool_))
        else:
            spec.append(("s", k, False))
    return spec, tensors


def _build_key(spec, arrays):
    out = []
    for kind, v, is_bool in spec:
        if kind == "t":
            a = arrays[v]
            if jnp.issubdtype(a.dtype, jnp.integer):
                a = a.astype(jnp.int32)
            out.append(a)
        elif kind == "a":
            out.append(v)
        else:
            out.append(v)
    return tuple(out)


def _getitem(self, key):
    spec, tensors = _prep_index(key)
    has_bool = builtins.any(b for _, _, b in spec)
    if has_bool:
        # boolean masking → dynamic shape: eager numpy path
        arr = np.asarray(self._data)
        np_key = tuple(
            np.asarray(tensors[v]._data) if kind == "t" else (np.asarray(v) if kind == "a" else v)
            for kind, v, _ in spec
        )
        return wrap(jnp.asarray(arr[np_key if len(np_key) > 1 else np_key[0]]))

    def f(a, *idx_arrays):
        k = _build_key(spec, idx_arrays)
        return a[k if len(k) > 1 else k[0]]

    return apply(f, [self] + tensors, name="getitem")


def _setitem(self, key, value):
    spec, tensors = _prep_index(key)
    is_value_tensor = isinstance(value, (Tensor, np.ndarray, list)) or (
        not isinstance(value, (int, float, bool))
    )
    inputs = [self]
    if is_value_tensor:
        value = coerce(value)
        inputs.append(value)
    inputs += tensors

    def f(a, *rest):
        if is_value_tensor:
            v, idx_arrays = rest[0], rest[1:]
        else:
            v, idx_arrays = value, rest
        k = _build_key(spec, idx_arrays)
        k = k if len(k) > 1 else k[0]
        if hasattr(v, "astype") and hasattr(v, "dtype") and v.dtype != a.dtype:
            v = v.astype(a.dtype)
        return a.at[k].set(v)

    out = apply(f, inputs, name="setitem")
    return inplace_rebind(self, out)


Tensor.__getitem__ = _getitem
Tensor.__setitem__ = _setitem


# ---------------------------------------------------------------------------
# operator protocol
# ---------------------------------------------------------------------------

Tensor.__add__ = lambda s, o: _math.add(s, o)
Tensor.__radd__ = lambda s, o: _math.add(o, s)
Tensor.__sub__ = lambda s, o: _math.subtract(s, o)
Tensor.__rsub__ = lambda s, o: _math.subtract(o, s)
Tensor.__mul__ = lambda s, o: _math.multiply(s, o)
Tensor.__rmul__ = lambda s, o: _math.multiply(o, s)
Tensor.__truediv__ = lambda s, o: _math.divide(s, o)
Tensor.__rtruediv__ = lambda s, o: _math.divide(o, s)
Tensor.__floordiv__ = lambda s, o: _math.floor_divide(s, o)
Tensor.__rfloordiv__ = lambda s, o: _math.floor_divide(o, s)
Tensor.__mod__ = lambda s, o: _math.remainder(s, o)
Tensor.__rmod__ = lambda s, o: _math.remainder(o, s)
Tensor.__pow__ = lambda s, o: _math.pow(s, o)
Tensor.__rpow__ = lambda s, o: _math.pow(o, s)
Tensor.__matmul__ = lambda s, o: _math.matmul(s, o)
Tensor.__rmatmul__ = lambda s, o: _math.matmul(o, s)
Tensor.__neg__ = lambda s: _math.neg(s)
Tensor.__abs__ = lambda s: _math.abs(s)
Tensor.__invert__ = lambda s: _math.logical_not(s) if s.dtype == "bool" else _math.bitwise_not(s)
Tensor.__and__ = lambda s, o: _math.logical_and(s, o) if s.dtype == "bool" else _math.bitwise_and(s, o)
Tensor.__or__ = lambda s, o: _math.logical_or(s, o) if s.dtype == "bool" else _math.bitwise_or(s, o)
Tensor.__xor__ = lambda s, o: _math.logical_xor(s, o) if s.dtype == "bool" else _math.bitwise_xor(s, o)
Tensor.__eq__ = lambda s, o: _math.equal(s, o)
Tensor.__ne__ = lambda s, o: _math.not_equal(s, o)
Tensor.__lt__ = lambda s, o: _math.less_than(s, o)
Tensor.__le__ = lambda s, o: _math.less_equal(s, o)
Tensor.__gt__ = lambda s, o: _math.greater_than(s, o)
Tensor.__ge__ = lambda s, o: _math.greater_equal(s, o)

Tensor.__iadd__ = lambda s, o: _math.add_(s, o)
Tensor.__isub__ = lambda s, o: _math.subtract_(s, o)
Tensor.__imul__ = lambda s, o: _math.multiply_(s, o)
Tensor.__itruediv__ = lambda s, o: _math.divide_(s, o)


# ---------------------------------------------------------------------------
# method patching (x.foo(...) == ops.foo(x, ...))
# ---------------------------------------------------------------------------

_METHOD_SOURCES = (_math, _creation, _manipulation, _reduction, _search, _random, _linalg)

_SKIP = {
    "zeros", "ones", "full", "empty", "arange", "linspace", "logspace", "eye",
    "meshgrid", "rand", "randn", "randint", "randperm", "uniform", "normal",
    "gaussian", "standard_normal", "is_tensor", "broadcast_shape",
    "scatter_nd", "complex",
}


def _patch_methods():
    for mod in _METHOD_SOURCES:
        for name in dir(mod):
            if name.startswith("_") or name in _SKIP:
                continue
            fn = getattr(mod, name)
            if not callable(fn) or isinstance(fn, type):
                continue
            if getattr(fn, "__module__", "").startswith("jax"):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)


_patch_methods()

# paddle-specific method aliases
Tensor.mean = _reduction.mean
Tensor.sum = _reduction.sum
Tensor.max = _reduction.max
Tensor.min = _reduction.min
Tensor.matmul = _math.matmul
Tensor.mm = _math.mm
Tensor.dot = _math.dot
Tensor.t = _manipulation.t
Tensor.reshape = _manipulation.reshape
Tensor.unsqueeze = _manipulation.unsqueeze
Tensor.squeeze = _manipulation.squeeze
Tensor.fill_ = _manipulation.fill_
Tensor.zero_ = _manipulation.zero_
Tensor.uniform_ = _random.uniform_
Tensor.normal_ = _random.normal_
Tensor.set_value = _manipulation.set_value_

# round-5 method aliases (reference Tensor surface / torch-compat names)
Tensor.ndimension = lambda s: s.ndim
Tensor.nelement = lambda s: s.size
Tensor.sub = _math.subtract
Tensor.sub_ = _math.subtract_
Tensor.mul = _math.multiply
Tensor.mul_ = _math.multiply_
Tensor.div = _math.divide
Tensor.div_ = _math.divide_
Tensor.clamp = _math.clip
Tensor.clamp_ = _math.clip_
Tensor.T = property(lambda s: _manipulation.transpose(s))  # perm=None reverses
Tensor.mT = property(
    lambda s: _manipulation.transpose(
        s, list(range(s.ndim - 2)) + [s.ndim - 1, s.ndim - 2]
    )
)


def _copy_(self, other):
    """In-place copy from another tensor (reference: Tensor.copy_ requires
    matching shapes); payload replacement delegates to set_value_."""
    from .dispatch import coerce

    other = coerce(other)
    if tuple(other.shape) != tuple(self.shape):
        raise ValueError(
            f"copy_: shape mismatch — source {list(other.shape)} vs "
            f"destination {list(self.shape)}"
        )
    return _manipulation.set_value_(self, other)


Tensor.copy_ = _copy_


def _retain_grads(self):
    """Make .grad available on a non-leaf after backward (reference:
    Tensor.retain_grads): a weak grad hook accumulates the cotangent into
    .grad — the engine already applies output hooks to non-leaves."""
    if getattr(self, "_retains_grad", False):
        return self
    self._retains_grad = True
    import weakref

    wr = weakref.ref(self)

    def hook(g):
        t_ = wr()
        if t_ is not None:
            t_.grad = g if t_.grad is None else t_.grad + g
        return g

    self.register_hook(hook)
    return self


Tensor.retain_grads = _retain_grads
