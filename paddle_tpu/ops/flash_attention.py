"""Flash attention — TPU-native (reference capability:
paddle/phi/kernels/gpu/flash_attn_kernel.cu wrapping the FlashAttention CUDA
library; here a Pallas TPU kernel + an XLA blockwise fallback).

Layout convention follows the reference API: [batch, seq, num_heads, head_dim].

Design (see /opt/skills/guides/pallas_guide.md):
- forward: online-softmax blockwise kernel; grid over (batch*heads, q blocks);
  K/V streamed through VMEM; causal masking applied per block.
- backward: blockwise recompute (flash-attention-2 style) expressed in JAX —
  XLA fuses it well on TPU; a hand-written Pallas backward is a later
  optimization.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..framework import core as _core
from ..tensor import Tensor
from .dispatch import apply, coerce

_NEG_INF = -1e30


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, scale, block_q, block_k, seq_len):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32) * scale  # [block_q, d]

    m = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    num_k_blocks = seq_len // block_k
    q_start = qi * block_q

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_ids = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(-1)
        acc_new = acc * alpha[:, None] + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    upper = (q_start + block_q + block_k - 1) // block_k if causal else num_k_blocks
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m, l, acc))
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _pallas_flash_forward(q, k, v, causal, scale, block_q=256, block_k=256):
    """q,k,v: [bh, seq, d] — returns [bh, seq, d]."""
    from jax.experimental import pallas as pl

    bh, seq_len, d = q.shape
    block_q = min(block_q, seq_len)
    block_k = min(block_k, seq_len)
    grid = (bh, seq_len // block_q)

    kernel = functools.partial(
        _flash_fwd_kernel,
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        seq_len=seq_len,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, seq_len, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
    )(q, k, v)


# ---------------------------------------------------------------------------
# Blockwise XLA fallback (O(seq) memory via scan + checkpoint)
# ---------------------------------------------------------------------------


def _blockwise_attention(q, k, v, mask, causal, scale, block_k=512):
    """q: [b, h, sq, d]; k,v: [b, h, sk, d]; mask broadcastable [b, h, sq, sk]."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if sk <= block_k or sk % block_k != 0:
        return _dense_attention(q, k, v, mask, causal, scale)

    qf = q.astype(jnp.float32) * scale
    nblocks = sk // block_k

    def body(carry, ki):
        m, l, acc = carry
        ks = lax.dynamic_slice_in_dim(k, ki * block_k, block_k, axis=2).astype(jnp.float32)
        vs = lax.dynamic_slice_in_dim(v, ki * block_k, block_k, axis=2).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, ks)
        if causal:
            q_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, block_k), 0)
            k_ids = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (sq, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        if mask is not None:
            msk = lax.dynamic_slice_in_dim(mask, ki * block_k, block_k, axis=-1)
            s = s + msk.astype(s.dtype)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(-1)
        acc_new = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vs)
        return (m_new, l_new, acc_new), None

    init = (
        jnp.full((b, h, sq), _NEG_INF, jnp.float32),
        jnp.zeros((b, h, sq), jnp.float32),
        jnp.zeros((b, h, sq, d), jnp.float32),
    )
    (m, l, acc), _ = lax.scan(jax.checkpoint(body), init, jnp.arange(nblocks))
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def _dense_attention(q, k, v, mask, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    sq, sk = q.shape[2], k.shape[2]
    if causal:
        q_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_ids >= k_ids - (sk - sq), s, _NEG_INF)
    if mask is not None:
        s = s + mask.astype(s.dtype)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


# ---------------------------------------------------------------------------
# public entry — jax-level (arrays in, arrays out; custom_vjp around pallas)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_core(q, k, v, causal, scale):
    return _flash_fwd_impl(q, k, v, causal, scale)


def _flash_fwd_impl(q, k, v, causal, scale):
    """q,k,v: [b, h, s, d]."""
    b, h, s, d = q.shape
    use_pallas = (
        _on_tpu()
        and s % 128 == 0
        and d <= 256
        and q.shape == k.shape
    )
    if use_pallas:
        qf = q.reshape(b * h, s, d)
        kf = k.reshape(b * h, s, d)
        vf = v.reshape(b * h, s, d)
        out = _pallas_flash_forward(qf, kf, vf, causal, scale)
        return out.reshape(b, h, s, d)
    return _blockwise_attention(q, k, v, None, causal, scale)


def _flash_fwd_rule(q, k, v, causal, scale):
    out = _flash_fwd_impl(q, k, v, causal, scale)
    return out, (q, k, v)


def _flash_bwd_rule(causal, scale, res, g):
    q, k, v = res
    # flash-2-style recompute backward, expressed for XLA
    _, vjp = jax.vjp(lambda q_, k_, v_: _blockwise_attention(q_, k_, v_, None, causal, scale), q, k, v)
    return vjp(g)


_flash_attention_core.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def sdpa_array(q, k, v, mask=None, causal=False, scale=None):
    """Array-level SDPA used by models and by the Tensor-level op below.

    q,k,v: [batch, seq, heads, dim] → out [batch, seq, heads, dim].
    """
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # grouped-query attention: expand kv heads if fewer than q heads
    hq, hk = qt.shape[1], kt.shape[1]
    if hk != hq:
        rep = hq // hk
        kt = jnp.repeat(kt, rep, axis=1)
        vt = jnp.repeat(vt, rep, axis=1)
    if mask is None:
        out = _flash_attention_core(qt, kt, vt, causal, scale)
    else:
        out = _dense_attention(qt, kt, vt, mask, causal, scale)
    return jnp.transpose(out, (0, 2, 1, 3))


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True
):
    query, key, value = coerce(query), coerce(key), coerce(value)
    ins = [query, key, value]
    has_mask = attn_mask is not None
    if has_mask:
        mask = coerce(attn_mask)
        if mask.dtype == "bool":
            from . import cast as _  # noqa

            mask = apply(
                lambda m: jnp.where(m, 0.0, _NEG_INF).astype(jnp.float32), [mask]
            )
        ins.append(mask)

    def f(q, k, v, *m):
        return sdpa_array(q, k, v, m[0] if m else None, is_causal)

    out = apply(f, ins, name="flash_attention")
    if dropout_p > 0.0 and training:
        from ..nn.functional import dropout as _dropout

        out = _dropout(out, dropout_p, training=training)
    return out
