"""Flash attention — TPU-native (reference capability:
paddle/phi/kernels/gpu/flash_attn_kernel.cu wrapping the FlashAttention CUDA
library; here a Pallas TPU kernel + an XLA blockwise fallback).

Layout convention follows the reference API: [batch, seq, num_heads, head_dim].

Design (see /opt/skills/guides/pallas_guide.md):
- forward: online-softmax kernel; grid (batch*heads, q blocks, k blocks)
  with k innermost — each step DMAs ONE [block_k, d] K/V tile through VMEM
  and carries (m, l, acc) in VMEM scratch across the sequential grid, so
  sequence length is bounded by HBM, not VMEM (32k+ works).
- backward: hand-written FA-2 kernels — dkdv (grid over k blocks, q
  streamed) and dq (grid over q blocks, k streamed) — recomputing p from
  (q, k, lse); delta = rowsum(g*out) precomputed outside.  An XLA blockwise
  path remains as fallback for masks/odd shapes and as the parity oracle.
- varlen: packed sequences with SEGMENT IDS (the static-shape TPU encoding
  of the reference's flash_attn_varlen cu_seqlens API): attention is masked
  to seg_q == seg_k in the kernels; `flash_attn_varlen` converts cu_seqlens
  to segment ids.
"""

from __future__ import annotations

import functools
import math
import threading

import jax
import jax.numpy as jnp
from jax import lax

from ..framework import core as _core
from ..tensor import Tensor
from .dispatch import apply, coerce

_NEG_INF = -1e30


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def _blk_mask(s, q_start, k_start, block_q, block_k, causal, sq=None, sk=None):
    """Apply causal and/or segment masking to a [block_q, block_k] score
    block.  sq/sk: per-row/col segment ids (or None).  q_start may carry a
    global offset (context-parallel rectangular causal blocks)."""
    masked = s
    if causal:
        q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        masked = jnp.where(q_ids >= k_ids, masked, _NEG_INF)
    if sq is not None:
        masked = jnp.where(sq[:, None] == sk[None, :], masked, _NEG_INF)
    return masked


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, causal, scale, block_q, block_k, seg_refs=(), carry_refs=(),
    off_ref=None, kb_ref=None,
):
    """Grid (bh blocks, q blocks, k blocks), k innermost: one K/V tile per
    step, (m, l, acc) carried in VMEM scratch across the sequential grid.
    All refs carry a leading block_bh dim — batching several (batch, head)
    rows per grid step amortizes the per-step overhead that dominates at
    short seq / many heads (BERT-384 measured ~10% MXU eff at bb=1)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)
    if off_ref is not None:
        # per-q-block ABSOLUTE start positions (context-parallel
        # rectangular causal blocks; zig-zag q halves have different
        # global offsets, so each block carries its own)
        q_start = off_ref[qi]
    else:
        q_start = qi * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        if carry_refs:
            # continuation: previous partial (out, lse) is algebraically a
            # pseudo-block with m=lse, l=1, acc=out — the ring-attention
            # hop merge happens IN-KERNEL instead of as a separate
            # elementwise chain per hop
            m_scr[...] = carry_refs[1][...].astype(jnp.float32)
            l_scr[...] = jnp.ones_like(l_scr)
            acc_scr[...] = carry_refs[0][...].astype(jnp.float32)
        else:
            m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: blocks strictly above the diagonal contribute nothing
    needed = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(needed)
    def _compute():
        q = q_ref[...]  # [bb, block_q, d] — half precision operands for the MXU
        k = k_ref[...]
        v = v_ref[...]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale  # [bb, block_q, block_k]
        if kb_ref is not None:
            # additive key bias (lowered key-padding attn_mask): one value
            # per key column, broadcast over the q rows exactly as the XLA
            # fallback's `s + mask`
            s = s + kb_ref[:, 0][None, None, :]
        sq = sk = None
        if seg_refs:
            sq = seg_refs[0][:, 0]
            sk = seg_refs[1][:, 0]
        s = _blk_mask(s, q_start, k_start, block_q, block_k, causal, sq, sk)
        m = m_scr[..., 0]  # [bb, block_q]
        l = l_scr[..., 0]
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        m_scr[...] = m_new[..., None]
        l_scr[...] = (alpha * l + p.sum(-1))[..., None]
        acc_scr[...] = acc_scr[...] * alpha[..., None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_scr[..., 0], 1e-30)
        o_ref[...] = (acc_scr[...] / l_safe[..., None]).astype(o_ref.dtype)
        lse_ref[...] = (m_scr[..., 0] + jnp.log(l_safe))[..., None]


def q_block_starts(offsets_and_lens, bq):
    """Per-q-block absolute start positions for a q tensor formed by
    concatenating chunks: [(global_offset, rows), ...] -> int32 array.
    `bq` must divide every chunk's row count (blocks may not straddle
    chunks — rows within a block share one contiguous global range)."""
    starts = []
    for off, n in offsets_and_lens:
        assert n % bq == 0, (n, bq)
        for r in range(0, n, bq):
            starts.append(off + r)
    return jnp.stack([jnp.asarray(o, jnp.int32) for o in starts])


def _pick_block(seq_len, pref):
    """Largest multiple-of-128 divisor of seq_len that is <= pref: big
    blocks amortize the per-grid-step q reload (seq 384 must pick 384, not
    128 — a 3x3 grid of tiny programs measurably regressed BERT)."""
    best = 128
    b = 128
    while b <= min(seq_len, pref):
        if seq_len % b == 0:
            best = b
        b += 128
    return best


def _pick_bh_block(bh, n_heads, block_q, block_k, d, has_segments):
    """How many (batch, head) rows to process per grid step.  Budgeted by
    the [bb, block_q, block_k] fp32 score/prob temporaries (~2 live copies)
    against ~8MB of the ~16MB VMEM; long sequences naturally get bb=1.
    With segment ids the bh block must stay within one batch row, so bb
    must divide n_heads."""
    per_bb = block_q * block_k * 4 * 2 + 4 * block_q * d * 4
    limit = max(1, (8 * 1024 * 1024) // max(per_bb, 1))
    cand = n_heads if has_segments else bh
    best = 1
    for bb in range(1, min(limit, cand) + 1):
        if cand % bb == 0 and bh % bb == 0:
            best = bb
    return best


def _pallas_flash_forward(q, k, v, causal, scale, segments=None, n_heads=1,
                          block_q=1024, block_k=1024, interpret=False,
                          carry=None, out_dtype=None, q_offset=None,
                          kbias=None):
    """q,k,v: [bh, seq, d]; segments: optional [b, seq, 1] int32 (shared
    across the head dim via the index map); carry: optional
    (out_prev [bh, seq, d], lse_prev [bh, seq, 1]) continuation state —
    this call merges its blocks ONTO the carry (ring-attention hops);
    q_offset: optional int32 [seq/block_q] (may be traced) — ABSOLUTE
    global start position of each q block, for rectangular causal blocks
    whose rows are not contiguous in global positions (zig-zag context
    parallelism); build with q_block_starts().
    kbias: optional [b, k_len, 1] f32 additive per-key bias (a lowered
    key-padding attn_mask), shared across heads via the index map.
    Returns (out [bh, seq, d], lse [bh, seq, 1] f32)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_len, d = q.shape
    k_len = k.shape[1]
    # block sizes must divide the sequence (the caller guarantees s % 128
    # == 0, so 128 always works)
    block_q = _pick_block(seq_len, block_q)
    block_k = _pick_block(k_len, block_k)
    per_batch = segments is not None or kbias is not None
    bb = _pick_bh_block(bh, n_heads, block_q, block_k, d, per_batch)
    grid = (bh // bb, seq_len // block_q, k_len // block_k)

    in_specs = [
        pl.BlockSpec((bb, block_q, d), lambda b, i, j, *_: (b, i, 0)),
        pl.BlockSpec((bb, block_k, d), lambda b, i, j, *_: (b, j, 0)),
        pl.BlockSpec((bb, block_k, d), lambda b, i, j, *_: (b, j, 0)),
    ]
    args = [q, k, v]
    if segments is not None:
        # bb divides n_heads, so one bh block maps to exactly one batch row
        in_specs += [
            pl.BlockSpec((None, block_q, 1), lambda b, i, j, *_: ((b * bb) // n_heads, i, 0)),
            pl.BlockSpec((None, block_k, 1), lambda b, i, j, *_: ((b * bb) // n_heads, j, 0)),
        ]
        args += [segments, segments]
    if kbias is not None:
        in_specs += [
            pl.BlockSpec((None, block_k, 1), lambda b, i, j, *_: ((b * bb) // n_heads, j, 0)),
        ]
        args += [kbias]
    if carry is not None:
        in_specs += [
            pl.BlockSpec((bb, block_q, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((bb, block_q, 1), lambda b, i, j, *_: (b, i, 0)),
        ]
        args += [carry[0], carry[1]]

    def kernel(*refs):
        if q_offset is not None:
            off_ref, refs = refs[0], refs[1:]
        else:
            off_ref = None
        q_ref, k_ref, v_ref, *rest = refs
        if segments is not None:
            seg_refs, rest = rest[:2], rest[2:]
        else:
            seg_refs = ()
        if kbias is not None:
            kb_ref, rest = rest[0], rest[1:]
        else:
            kb_ref = None
        if carry is not None:
            carry_refs, rest = rest[:2], rest[2:]
        else:
            carry_refs = ()
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
        _flash_fwd_kernel(
            q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
            causal=causal, scale=scale, block_q=block_q, block_k=block_k,
            seg_refs=seg_refs, carry_refs=carry_refs, off_ref=off_ref,
            kb_ref=kb_ref,
        )

    out_specs = [
        pl.BlockSpec((bb, block_q, d), lambda b, i, j, *_: (b, i, 0)),
        # [bh, seq, 1] — a trailing unit dim keeps the block TPU-tileable
        pl.BlockSpec((bb, block_q, 1), lambda b, i, j, *_: (b, i, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct(q.shape, out_dtype or q.dtype),
        jax.ShapeDtypeStruct((bh, seq_len, 1), jnp.float32),
    ]
    scratch = [
        pltpu.VMEM((bb, block_q, 1), jnp.float32),
        pltpu.VMEM((bb, block_q, 1), jnp.float32),
        pltpu.VMEM((bb, block_q, d), jnp.float32),
    ]
    if q_offset is not None:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=grid, in_specs=in_specs,
            out_specs=out_specs, scratch_shapes=scratch,
        )
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interpret,
        )(jnp.asarray(q_offset, jnp.int32).reshape(-1), *args)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# Pallas backward kernels (FA-2: recompute p from q,k,lse; delta precomputed)
# ---------------------------------------------------------------------------


def _flash_bwd_dkdv_kernel(
    q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, causal, scale, block_q, block_k, seg_refs=(),
    off_ref=None, kb_ref=None,
):
    """Grid (bh, k blocks, q blocks), q innermost; dk/dv accumulate in
    scratch across the q sweep."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)
    k_start = ki * block_k
    q_start = off_ref[qi] if off_ref is not None else qi * block_q

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    needed = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(needed)
    def _compute():
        q = q_ref[...]  # [bb, block_q, d]
        k = k_ref[...]
        v = v_ref[...]
        g = g_ref[...]
        lse = lse_ref[..., 0]  # [bb, block_q]
        delta = delta_ref[..., 0]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale  # [bb, bq, bk]
        if kb_ref is not None:
            s = s + kb_ref[:, 0][None, None, :]
        sq = sk = None
        if seg_refs:
            sq = seg_refs[0][:, 0]
            sk = seg_refs[1][:, 0]
        s = _blk_mask(s, q_start, k_start, block_q, block_k, causal, sq, sk)
        p = jnp.exp(s - lse[..., None])  # [bb, bq, bk] f32
        pb = p.astype(g.dtype)
        dv_scr[...] += jax.lax.dot_general(
            pb, g, (((1,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )  # [bb, bk, d]
        dp = jax.lax.dot_general(
            g, v, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )  # [bb, bq, bk]
        ds = (p * (dp - delta[..., None]) * scale).astype(q.dtype)
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((1,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )  # [bb, bk, d]

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, causal, scale, block_q, block_k, seg_refs=(), off_ref=None,
    kb_ref=None,
):
    """Grid (bh, q blocks, k blocks), k innermost; dq accumulates in
    scratch across the k sweep."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)
    q_start = off_ref[qi] if off_ref is not None else qi * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    needed = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(needed)
    def _compute():
        q = q_ref[...]  # [bb, block_q, d]
        k = k_ref[...]
        v = v_ref[...]
        g = g_ref[...]
        lse = lse_ref[..., 0]
        delta = delta_ref[..., 0]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale
        if kb_ref is not None:
            s = s + kb_ref[:, 0][None, None, :]
        sq = sk = None
        if seg_refs:
            sq = seg_refs[0][:, 0]
            sk = seg_refs[1][:, 0]
        s = _blk_mask(s, q_start, k_start, block_q, block_k, causal, sq, sk)
        p = jnp.exp(s - lse[..., None])
        dp = jax.lax.dot_general(
            g, v, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta[..., None]) * scale).astype(q.dtype)
        dq_scr[...] += jax.lax.dot_general(
            ds, k, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_k - 1)
    def _finish():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)


def _pallas_flash_backward(q, k, v, g, out, lse, causal, scale, segments=None,
                           n_heads=1, block_q=1024, block_k=1024, interpret=False,
                           delta=None, q_offset=None, kbias=None):
    """q/g/out/lse: [bh, sq, ...]; k/v: [bh, sk, d] — rectangular k is
    allowed (causal with sq != sk requires q_offset: absolute per-q-block
    start positions; without q_offset, causal assumes sq == sk).
    delta: optional precomputed rowsum(g*out) [bh, sq, 1] — the ring path
    computes it ONCE for all hops instead of once per hop.
    kbias: optional [b, sk, 1] f32 additive per-key bias (same operand as
    the forward pass — s must be recomputed identically for p to match).
    Returns (dq, dk, dv)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(s, block_q)
    block_k = _pick_block(sk, block_k)
    per_batch = segments is not None or kbias is not None
    bb = _pick_bh_block(bh, n_heads, block_q, block_k, d, per_batch)
    if delta is None:
        delta = jnp.sum(
            g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
        )  # [bh, s, 1]

    common = dict(causal=causal, scale=scale, block_q=block_q, block_k=block_k)

    # -- dk/dv: grid over k blocks, stream q --------------------------------
    in_specs = [
        pl.BlockSpec((bb, block_q, d), lambda b, i, j, *_: (b, j, 0)),  # q
        pl.BlockSpec((bb, block_k, d), lambda b, i, j, *_: (b, i, 0)),  # k
        pl.BlockSpec((bb, block_k, d), lambda b, i, j, *_: (b, i, 0)),  # v
        pl.BlockSpec((bb, block_q, d), lambda b, i, j, *_: (b, j, 0)),  # g
        pl.BlockSpec((bb, block_q, 1), lambda b, i, j, *_: (b, j, 0)),  # lse
        pl.BlockSpec((bb, block_q, 1), lambda b, i, j, *_: (b, j, 0)),  # delta
    ]
    args = [q, k, v, g, lse, delta]
    if segments is not None:
        in_specs += [
            pl.BlockSpec((None, block_q, 1), lambda b, i, j, *_: ((b * bb) // n_heads, j, 0)),
            pl.BlockSpec((None, block_k, 1), lambda b, i, j, *_: ((b * bb) // n_heads, i, 0)),
        ]
        args += [segments, segments]
    if kbias is not None:
        in_specs += [
            pl.BlockSpec((None, block_k, 1), lambda b, i, j, *_: ((b * bb) // n_heads, i, 0)),
        ]
        args += [kbias]

    def dkdv_kernel(*refs):
        if q_offset is not None:
            off_ref, refs = refs[0], refs[1:]
        else:
            off_ref = None
        q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, *rest = refs
        if segments is not None:
            seg_refs, rest = rest[:2], rest[2:]
        else:
            seg_refs = ()
        kb_ref = rest[0] if kbias is not None else None
        dk_ref, dv_ref, dk_scr, dv_scr = rest[-4:]
        _flash_bwd_dkdv_kernel(
            q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dk_ref, dv_ref,
            dk_scr, dv_scr, seg_refs=seg_refs, off_ref=off_ref, kb_ref=kb_ref,
            **common,
        )

    dkdv_grid = (bh // bb, sk // block_k, s // block_q)
    dkdv_out_specs = [
        pl.BlockSpec((bb, block_k, d), lambda b, i, j, *_: (b, i, 0)),
        pl.BlockSpec((bb, block_k, d), lambda b, i, j, *_: (b, i, 0)),
    ]
    dkdv_out_shape = [
        jax.ShapeDtypeStruct(k.shape, k.dtype),
        jax.ShapeDtypeStruct(v.shape, v.dtype),
    ]
    dkdv_scratch = [
        pltpu.VMEM((bb, block_k, d), jnp.float32),
        pltpu.VMEM((bb, block_k, d), jnp.float32),
    ]
    if q_offset is not None:
        off_arr = jnp.asarray(q_offset, jnp.int32).reshape(-1)
        dk, dv = pl.pallas_call(
            dkdv_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1, grid=dkdv_grid, in_specs=in_specs,
                out_specs=dkdv_out_specs, scratch_shapes=dkdv_scratch,
            ),
            out_shape=dkdv_out_shape,
            interpret=interpret,
        )(off_arr, *args)
    else:
        dk, dv = pl.pallas_call(
            dkdv_kernel,
            grid=dkdv_grid,
            in_specs=in_specs,
            out_specs=dkdv_out_specs,
            out_shape=dkdv_out_shape,
            scratch_shapes=dkdv_scratch,
            interpret=interpret,
        )(*args)

    # -- dq: grid over q blocks, stream k -----------------------------------
    in_specs = [
        pl.BlockSpec((bb, block_q, d), lambda b, i, j, *_: (b, i, 0)),  # q
        pl.BlockSpec((bb, block_k, d), lambda b, i, j, *_: (b, j, 0)),  # k
        pl.BlockSpec((bb, block_k, d), lambda b, i, j, *_: (b, j, 0)),  # v
        pl.BlockSpec((bb, block_q, d), lambda b, i, j, *_: (b, i, 0)),  # g
        pl.BlockSpec((bb, block_q, 1), lambda b, i, j, *_: (b, i, 0)),  # lse
        pl.BlockSpec((bb, block_q, 1), lambda b, i, j, *_: (b, i, 0)),  # delta
    ]
    args = [q, k, v, g, lse, delta]
    if segments is not None:
        in_specs += [
            pl.BlockSpec((None, block_q, 1), lambda b, i, j, *_: ((b * bb) // n_heads, i, 0)),
            pl.BlockSpec((None, block_k, 1), lambda b, i, j, *_: ((b * bb) // n_heads, j, 0)),
        ]
        args += [segments, segments]
    if kbias is not None:
        in_specs += [
            pl.BlockSpec((None, block_k, 1), lambda b, i, j, *_: ((b * bb) // n_heads, j, 0)),
        ]
        args += [kbias]

    def dq_kernel(*refs):
        if q_offset is not None:
            off_ref, refs = refs[0], refs[1:]
        else:
            off_ref = None
        q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, *rest = refs
        if segments is not None:
            seg_refs, rest = rest[:2], rest[2:]
        else:
            seg_refs = ()
        kb_ref = rest[0] if kbias is not None else None
        dq_ref, dq_scr = rest[-2:]
        _flash_bwd_dq_kernel(
            q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref, dq_scr,
            seg_refs=seg_refs, off_ref=off_ref, kb_ref=kb_ref, **common,
        )

    dq_grid = (bh // bb, s // block_q, sk // block_k)
    dq_out_spec = pl.BlockSpec((bb, block_q, d), lambda b, i, j, *_: (b, i, 0))
    dq_out_shape = jax.ShapeDtypeStruct(q.shape, q.dtype)
    dq_scratch = [pltpu.VMEM((bb, block_q, d), jnp.float32)]
    if q_offset is not None:
        dq = pl.pallas_call(
            dq_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1, grid=dq_grid, in_specs=in_specs,
                out_specs=dq_out_spec, scratch_shapes=dq_scratch,
            ),
            out_shape=dq_out_shape,
            interpret=interpret,
        )(off_arr, *args)
    else:
        dq = pl.pallas_call(
            dq_kernel,
            grid=dq_grid,
            in_specs=in_specs,
            out_specs=dq_out_spec,
            out_shape=dq_out_shape,
            scratch_shapes=dq_scratch,
            interpret=interpret,
        )(*args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Pallas decode kernel — q [sq small] vs a static KV cache [L], cache
# validity expressed IN-KERNEL from the write position (passed as a scalar)
# instead of an additive mask, so cached/serving attention never drops to
# the XLA fallback (reference: the inference runtime's flash-decode path,
# SURVEY §2.1 L8; round-4 verdict "flash-kernel decode attention").
# ---------------------------------------------------------------------------


def _decode_kernel(
    pos_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, scale, block_q, block_k,
):
    """Grid (bh blocks, q blocks, k blocks), k innermost.  Query row i of
    q-block qi sits at absolute position pos + qi*block_q + i and may attend
    cache slots j <= that position — which by construction covers exactly
    the written slots, so no separate validity mask exists anywhere."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)
    pos = pos_ref[0]
    q_start = qi * block_q
    k_start = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # blocks entirely beyond the last valid slot contribute nothing
    needed = k_start <= pos + q_start + block_q - 1

    @pl.when(needed)
    def _compute():
        q = q_ref[...]  # [bb, block_q, d]
        k = k_ref[...]
        v = v_ref[...]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale  # [bb, block_q, block_k]
        q_ids = pos + q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        m = m_scr[..., 0]
        l = l_scr[..., 0]
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        m_scr[...] = m_new[..., None]
        l_scr[...] = (alpha * l + p.sum(-1))[..., None]
        acc_scr[...] = acc_scr[...] * alpha[..., None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_scr[..., 0], 1e-30)
        o_ref[...] = (acc_scr[...] / l_safe[..., None]).astype(o_ref.dtype)


def _pallas_decode_forward(q, k, v, pos, scale, interpret=False):
    """q: [bh, sq, d] (sq pre-padded to the q block); k,v: [bh, L, d] cache
    buffers; pos: int32[1] scalar-prefetch.  Returns out [bh, sq, d]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = q.shape
    L = k.shape[1]
    block_q = sq if sq <= 256 else 128  # padded to 8/128 multiples by caller
    block_k = _pick_block(L, 512)
    # VMEM budget: score/prob temporaries + one K/V tile per bh row
    per_bb = block_q * block_k * 4 * 2 + 2 * block_k * d * 2 + 4 * block_q * d * 4
    limit = max(1, (8 * 1024 * 1024) // max(per_bb, 1))
    bb = 1
    for c in range(1, min(limit, bh) + 1):
        if bh % c == 0:
            bb = c
    grid = (bh // bb, sq // block_q, L // block_k)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, block_q, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((bb, block_k, d), lambda b, i, j, *_: (b, j, 0)),
            pl.BlockSpec((bb, block_k, d), lambda b, i, j, *_: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((bb, block_q, d), lambda b, i, j, *_: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((bb, block_q, 1), jnp.float32),
            pltpu.VMEM((bb, block_q, 1), jnp.float32),
            pltpu.VMEM((bb, block_q, d), jnp.float32),
        ],
    )

    def kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr):
        _decode_kernel(
            pos_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
            scale=scale, block_q=block_q, block_k=block_k,
        )

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(jnp.asarray(pos, jnp.int32).reshape(1), q, k, v)


def decode_attention_array(q, k, v, pos, scale=None):
    """Cached-attention for the static-KV decode path.

    q: [b, sq, h, d] (the fresh chunk); k,v: [b, L, kv_h, d] cache buffers
    (every slot, written or not); pos: scalar int32 — absolute position of
    q row 0 — or int32[b] PER-BATCH-ROW positions (the continuous-batching
    slot pool: each slot decodes at its own length, still one executable).
    Row i attends cache slots j <= pos + i.  Pallas on TPU (or under
    interpret); a fused dense XLA path elsewhere — both take validity from
    `pos`, never from a mask array.  Vector pos always takes the dense path
    (single-token decode is its domain and the dense matvec is the optimal
    lowering there anyway).

    Per-row pos composes with sq > 1: this is the speculative-decoding
    VERIFY contract (ISSUE 11).  A [b, k+1] draft window at per-slot
    positions runs one dense pass where window row i of slot s attends
    j <= pos[s] + i — row 0 reproduces the single-token decode step exactly
    (same reduction geometry per row), and the extra k rows are the
    near-free FLOPs speculation converts into accepted tokens.  Garbage
    cache rows beyond a slot's true length sit at j > pos + i and carry
    zero weight, so rejected-draft leftovers from a previous verify step
    are never attended before the next window overwrites them.
    """
    b, sq, h, d = q.shape
    per_row_pos = jnp.ndim(pos) == 1
    L = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    qt = jnp.transpose(q, (0, 2, 1, 3))  # [b, h, sq, d]
    kt = jnp.transpose(k, (0, 2, 1, 3))  # [b, hk, L, d] — NEVER repeated:
    vt = jnp.transpose(v, (0, 2, 1, 3))  # GQA groups share the cache as-is
    hk = kt.shape[1]
    rep = h // hk
    interpret = _FORCE_INTERPRET
    # kernel choice by q-chunk size: single-token (and small-chunk) decode
    # is a matvec per head — the dense XLA lowering fuses it into the
    # surrounding program with zero launch overhead and IS the optimal
    # flash-decode for q=1 (measured: Pallas per-layer launches cost ~30%
    # of decode tok/s).  The Pallas kernel wins for prefill-with-cache,
    # where it avoids materializing the [sq, L] score block.
    if (
        (_on_tpu() or interpret)
        and not per_row_pos
        and d <= 256
        and L % 128 == 0
        and sq >= 64
    ):
        # pad q rows up to the TPU sublane tile; padded rows attend slot 0+
        # legitimately (their q_ids exceed the real rows') and are sliced off.
        # The common serving shapes are already 8/128-aligned — hoist the
        # check so they take a zero-copy path (no per-group pad OR slice)
        sq_pad = -(-sq // 8) * 8 if sq <= 256 else -(-sq // 128) * 128
        needs_pad = sq_pad != sq
        _log_pallas_call("decode")
        kf = kt.reshape(b * hk, L, d)
        vf = vt.reshape(b * hk, L, d)
        # one kernel call per GQA group: q heads of group r run against the
        # UN-duplicated cache (a jnp.repeat would materialize rep copies of
        # the whole cache per layer per step)
        qg = qt.reshape(b, hk, rep, sq, d)
        outs = []
        for r in range(rep):
            qf = qg[:, :, r].reshape(b * hk, sq, d)
            if needs_pad:
                qf = jnp.pad(qf, ((0, 0), (0, sq_pad - sq), (0, 0)))
            o = _pallas_decode_forward(qf, kf, vf, pos, scale, interpret=interpret)
            if needs_pad:
                o = o[:, :sq]
            outs.append(o.reshape(b, hk, 1, sq, d))
        out = outs[0] if rep == 1 else jnp.concatenate(outs, axis=2)
        return jnp.transpose(out.reshape(b, h, sq, d), (0, 2, 1, 3))
    # dense path: grouped einsum chain (kv heads stay un-repeated; the GQA
    # broadcast happens inside the contraction), validity from pos
    q5 = qt.reshape(b, hk, rep, sq, d)
    s = jnp.einsum(
        "bgrqd,bgkd->bgrqk", q5, kt, preferred_element_type=jnp.float32
    ) * scale
    iota_q = jax.lax.broadcasted_iota(jnp.int32, (sq, L), 0)
    if per_row_pos:
        # [b, 1, 1, sq, L] broadcast against s [b, g, r, sq, L]
        q_ids = pos.reshape(b, 1, 1, 1, 1) + iota_q
    else:
        q_ids = pos + iota_q
    k_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, L), 1)
    s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bgrqk,bgkd->bgrqd", p.astype(vt.dtype), vt, preferred_element_type=jnp.float32
    ).astype(q.dtype)
    return jnp.transpose(out.reshape(b, h, sq, d), (0, 2, 1, 3))


def flash_decode(query, key, value, pos, scale=None):
    """Tensor-level cached-decode attention (see decode_attention_array)."""
    query, key, value, pos = coerce(query), coerce(key), coerce(value), coerce(pos)

    def f(q, k, v, p):
        return decode_attention_array(q, k, v, p, scale)

    return apply(f, [query, key, value, pos], name="flash_decode")


def paged_gather_kv(arena, tables, max_len):
    """Gather a paged arena [num_pages, page_size, kv_h, d] back into dense
    per-sequence buffers [b, max_len, kv_h, d] through the page tables
    ([b, P] int32).  The reshape-then-slice keeps the attended geometry
    identical to the dense slot pool (P * page_size >= max_len; the slack
    rows come from the sequence's own trailing page and are masked by pos
    downstream anyway)."""
    b = tables.shape[0]
    g = arena[tables]  # [b, P, page_size, kv_h, d]
    g = g.reshape(b, -1, arena.shape[2], arena.shape[3])
    return g[:, :max_len]


def _fused_paged_decode_forward(q, arena_k, arena_v, tables, pos, max_len,
                                scale, interpret=False):
    """Fused paged-decode attention: read the arena THROUGH the page tables
    in-kernel instead of materializing the gather (`paged_gather_kv` writes
    a dense [b, max_len, kv_h, d] copy of every sequence's KV to HBM each
    step — the single biggest HBM tax on the serving hot path; ROADMAP 4).

    q: [b, sq, h, d] (sq == 1 plain decode, sq == k+1 speculative verify);
    arena_k/v: [num_pages, page_size, kv_h, d]; tables: [b, P] int32 page
    ids (traced DATA — they index the arena inside the BlockSpec index
    maps, fed as scalar-prefetch so the DMA engine knows each page before
    its grid step); pos: int32 scalar or [b] per-slot positions.

    Grid (slot, kv head, page) with the page dim innermost-sequential: one
    [page_size, d] K/V tile streams through VMEM per step while online
    softmax (m, l, acc) carries in scratch — the same recurrence as
    `_flash_fwd_kernel`, but walking pages in table order.  Each slot's q
    rows for one kv head pack the whole GQA group x verify window
    ([rep * sq, d], row r = group member r // sq at window offset r % sq),
    so the un-duplicated cache tile is read ONCE per group.  In-kernel
    masks reproduce the gather path bit-for-bit: `jid <= pos + w` is the
    per-row causal/validity fence (also inert for inactive slots parked on
    scratch page 0 at pos 0) and `jid < max_len` reproduces the gather's
    `[:max_len]` slice of the trailing page's slack rows.

    Returns [b, sq, h, d]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    ps = arena_k.shape[1]
    hk = arena_k.shape[2]
    rep = h // hk
    P = tables.shape[1]
    R = rep * sq
    qr = -(-R // 8) * 8  # f32 sublane tile; pad rows are sliced off
    qt = jnp.transpose(q, (0, 2, 1, 3)).reshape(b, hk, rep, sq, d)
    qg = qt.reshape(b, hk, R, d)
    if qr != R:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, qr - R), (0, 0)))
    pos_v = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    tab = jnp.asarray(tables, jnp.int32).reshape(-1)

    def kernel(t_ref, p_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr):
        j = pl.program_id(2)
        n_p = pl.num_programs(2)
        p0 = p_ref[pl.program_id(0)]

        @pl.when(j == 0)
        def _init():
            m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)

        # pages entirely beyond the newest visible position (window row
        # sq-1 sees up to pos + sq - 1) contribute nothing
        needed = j * ps <= p0 + sq - 1

        @pl.when(needed)
        def _compute():
            qb = q_ref[...]  # [qr, d]
            kb = k_ref[...]  # [ps, d] — the page this table entry names
            vb = v_ref[...]
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [qr, ps]
            w = jax.lax.broadcasted_iota(jnp.int32, (qr, ps), 0) % sq
            jid = j * ps + jax.lax.broadcasted_iota(jnp.int32, (qr, ps), 1)
            s = jnp.where((jid <= p0 + w) & (jid < max_len), s, _NEG_INF)
            m = m_scr[..., 0]
            l = l_scr[..., 0]
            m_new = jnp.maximum(m, s.max(-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            m_scr[...] = m_new[..., None]
            l_scr[...] = (alpha * l + p.sum(-1))[..., None]
            acc_scr[...] = acc_scr[...] * alpha[..., None] + jax.lax.dot_general(
                p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        @pl.when(j == n_p - 1)
        def _finish():
            l_safe = jnp.maximum(l_scr[..., 0], 1e-30)
            o_ref[...] = (acc_scr[...] / l_safe[..., None]).astype(o_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hk, P),
        in_specs=[
            pl.BlockSpec((None, None, qr, d), lambda s, g, j, t, p: (s, g, 0, 0)),
            pl.BlockSpec(
                (None, ps, None, d), lambda s, g, j, t, p: (t[s * P + j], 0, g, 0)
            ),
            pl.BlockSpec(
                (None, ps, None, d), lambda s, g, j, t, p: (t[s * P + j], 0, g, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (None, None, qr, d), lambda s, g, j, t, p: (s, g, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((qr, 1), jnp.float32),
            pltpu.VMEM((qr, 1), jnp.float32),
            pltpu.VMEM((qr, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, qr, d), q.dtype),
        interpret=interpret,
    )(tab, pos_v, qg, arena_k, arena_v)
    out = out[:, :, :R].reshape(b, hk, rep, sq, d).reshape(b, h, sq, d)
    return jnp.transpose(out, (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _fused_paged_decode(q, arena_k, arena_v, tables, pos, max_len, scale,
                        interpret):
    """Differentiation-opaque wrapper: the dispatch layer's eager path
    computes a vjp over every op, and scalar-prefetch pallas_call has no JVP
    rule — decode is inference-only, so the vjp is declared (never pulled)
    via custom_vjp instead of traced through the kernel."""
    return _fused_paged_decode_forward(
        q, arena_k, arena_v, tables, pos, max_len, scale, interpret=interpret
    )


def _fused_paged_decode_fwd(q, arena_k, arena_v, tables, pos, max_len, scale,
                            interpret):
    out = _fused_paged_decode_forward(
        q, arena_k, arena_v, tables, pos, max_len, scale, interpret=interpret
    )
    return out, None


def _fused_paged_decode_bwd(max_len, scale, interpret, res, g):
    raise NotImplementedError(
        "fused paged decode attention is inference-only (no backward); "
        "differentiate through kernel='gather' instead"
    )


_fused_paged_decode.defvjp(_fused_paged_decode_fwd, _fused_paged_decode_bwd)


def _fused_paged_decode_quant_forward(q, arena_k, arena_v, k_scale, v_scale,
                                      tables, pos, max_len, scale,
                                      interpret=False):
    """`_fused_paged_decode_forward` over an int8 arena (ISSUE 18): the K/V
    page tiles arrive as int8 and their per-row scales ([page_size, 1]
    float32 tiles from the parallel scale arenas, addressed by the SAME
    `t[s*P+j]` table lookup in their BlockSpec index maps) ride into VMEM
    with them; dequantization — `tile.astype(f32) * scale_row` — happens
    per page tile inside the online-softmax loop, so the arena's HBM
    footprint is what streams: 1 byte per element plus 4 bytes per (row,
    head) instead of 2.  q is cast to f32 in-kernel so the dot runs at the
    dequantized precision the gather oracle uses — fused-vs-gather parity
    holds under quantization too.  Masks and the softmax recurrence are
    byte-identical to the unquantized kernel: scratch-page garbage scales
    are finite by construction and fenced by `jid <= pos + w` before they
    could reach a softmax."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    ps = arena_k.shape[1]
    hk = arena_k.shape[2]
    rep = h // hk
    P = tables.shape[1]
    R = rep * sq
    qr = -(-R // 8) * 8  # f32 sublane tile; pad rows are sliced off
    qt = jnp.transpose(q, (0, 2, 1, 3)).reshape(b, hk, rep, sq, d)
    qg = qt.reshape(b, hk, R, d)
    if qr != R:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, qr - R), (0, 0)))
    pos_v = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    tab = jnp.asarray(tables, jnp.int32).reshape(-1)

    def kernel(t_ref, p_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
               m_scr, l_scr, acc_scr):
        j = pl.program_id(2)
        n_p = pl.num_programs(2)
        p0 = p_ref[pl.program_id(0)]

        @pl.when(j == 0)
        def _init():
            m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)

        needed = j * ps <= p0 + sq - 1

        @pl.when(needed)
        def _compute():
            qb = q_ref[...].astype(jnp.float32)  # [qr, d]
            # in-VMEM dequant: int8 page tile * its [ps, 1] scale column
            kb = k_ref[...].astype(jnp.float32) * ks_ref[...]
            vb = v_ref[...].astype(jnp.float32) * vs_ref[...]
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [qr, ps]
            w = jax.lax.broadcasted_iota(jnp.int32, (qr, ps), 0) % sq
            jid = j * ps + jax.lax.broadcasted_iota(jnp.int32, (qr, ps), 1)
            s = jnp.where((jid <= p0 + w) & (jid < max_len), s, _NEG_INF)
            m = m_scr[..., 0]
            l = l_scr[..., 0]
            m_new = jnp.maximum(m, s.max(-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            m_scr[...] = m_new[..., None]
            l_scr[...] = (alpha * l + p.sum(-1))[..., None]
            acc_scr[...] = acc_scr[...] * alpha[..., None] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        @pl.when(j == n_p - 1)
        def _finish():
            l_safe = jnp.maximum(l_scr[..., 0], 1e-30)
            o_ref[...] = (acc_scr[...] / l_safe[..., None]).astype(o_ref.dtype)

    page_tile = pl.BlockSpec(
        (None, ps, None, d), lambda s, g, j, t, p: (t[s * P + j], 0, g, 0)
    )
    scale_tile = pl.BlockSpec(
        (None, ps, None, 1), lambda s, g, j, t, p: (t[s * P + j], 0, g, 0)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hk, P),
        in_specs=[
            pl.BlockSpec((None, None, qr, d), lambda s, g, j, t, p: (s, g, 0, 0)),
            page_tile,
            page_tile,
            scale_tile,
            scale_tile,
        ],
        out_specs=pl.BlockSpec(
            (None, None, qr, d), lambda s, g, j, t, p: (s, g, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((qr, 1), jnp.float32),
            pltpu.VMEM((qr, 1), jnp.float32),
            pltpu.VMEM((qr, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, qr, d), q.dtype),
        interpret=interpret,
    )(tab, pos_v, qg, arena_k, arena_v, k_scale, v_scale)
    out = out[:, :, :R].reshape(b, hk, rep, sq, d).reshape(b, h, sq, d)
    return jnp.transpose(out, (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9))
def _fused_paged_decode_quant(q, arena_k, arena_v, k_scale, v_scale, tables,
                              pos, max_len, scale, interpret):
    """Differentiation-opaque wrapper over the quantized fused kernel —
    same contract as `_fused_paged_decode` (decode is inference-only)."""
    return _fused_paged_decode_quant_forward(
        q, arena_k, arena_v, k_scale, v_scale, tables, pos, max_len, scale,
        interpret=interpret,
    )


def _fused_paged_decode_quant_fwd(q, arena_k, arena_v, k_scale, v_scale,
                                  tables, pos, max_len, scale, interpret):
    out = _fused_paged_decode_quant_forward(
        q, arena_k, arena_v, k_scale, v_scale, tables, pos, max_len, scale,
        interpret=interpret,
    )
    return out, None


def _fused_paged_decode_quant_bwd(max_len, scale, interpret, res, g):
    raise NotImplementedError(
        "quantized fused paged decode attention is inference-only (no "
        "backward); differentiate through kernel='gather' instead"
    )


_fused_paged_decode_quant.defvjp(
    _fused_paged_decode_quant_fwd, _fused_paged_decode_quant_bwd
)


def _fused_paged_viable(q, page_size):
    """Static eligibility for the fused paged kernel.  The arena page IS
    the kernel's K/V block, so page_size must be a sublane multiple; head
    dim is bounded by the same VMEM budget as the dense kernels."""
    if q.shape[3] > 256:
        return False, "paged head_dim > 256"
    if page_size % 8 != 0:
        return False, "paged page_size not 8-aligned"
    return True, None


def _fused_paged_decode_tp(q, arena_k, arena_v, tables, pos, max_len, scale,
                           interpret, mp):
    """Tensor-parallel dispatch of the fused kernel: `shard_map` over the
    'mp' mesh axis, q/arena/output split on their HEADS dim (axis 2) and
    tables/pos replicated, so each device's `pallas_call` streams only its
    local kv heads' pages.  GSPMD cannot partition a custom call — without
    the shard_map it would all-gather the whole arena onto every device.

    The GQA head packing keeps locality exact: q head `hk*rep + r` belongs
    to kv head `hk`, and contiguous 'mp' sharding of both head axes gives
    device d q heads [d*h/mp, (d+1)*h/mp) == the rep-block of its kv heads
    [d*hk/mp, (d+1)*hk/mp) — each local kernel is byte-identical to a
    single-device kernel over a model with h/mp heads.  check_rep=False:
    tables/pos stay replicated but the output is genuinely sharded."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..distributed import mesh as _mesh

    heads = P(None, None, "mp", None)
    fn = shard_map(
        lambda qq, ak, av, t, p: _fused_paged_decode(
            qq, ak, av, t, p, max_len, scale, interpret
        ),
        mesh=_mesh.get_mesh(),
        in_specs=(heads, heads, heads, P(None, None), P(None)),
        out_specs=heads,
        check_rep=False,
    )
    return fn(q, arena_k, arena_v, tables, pos)


def _fused_paged_decode_quant_tp(q, arena_k, arena_v, k_scale, v_scale,
                                 tables, pos, max_len, scale, interpret, mp):
    """Tensor-parallel dispatch of the QUANTIZED fused kernel: identical
    shard_map contract to `_fused_paged_decode_tp`, with the scale arenas
    riding the same kv-heads 'mp' sharding (their axis 2 is kv_heads too) —
    each device dequantizes only its local heads' pages in VMEM."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..distributed import mesh as _mesh

    heads = P(None, None, "mp", None)
    fn = shard_map(
        lambda qq, ak, av, ks, vs, t, p: _fused_paged_decode_quant(
            qq, ak, av, ks, vs, t, p, max_len, scale, interpret
        ),
        mesh=_mesh.get_mesh(),
        in_specs=(heads, heads, heads, heads, heads, P(None, None), P(None)),
        out_specs=heads,
        check_rep=False,
    )
    return fn(q, arena_k, arena_v, k_scale, v_scale, tables, pos)


def _fused_paged_decode_partials_forward(q, arena_k, arena_v, tables,
                                         page_base, pos, max_len, scale,
                                         interpret=False, k_scale=None,
                                         v_scale=None):
    """The fused paged-decode kernel in PARTIALS form, for context-parallel
    decode (ISSUE 20): identical page-walk, GQA/verify packing, and online-
    softmax recurrence to `_fused_paged_decode_forward`, with two changes.

    (1) Table columns no longer imply token positions.  Under cp, shard s
    holds sequence pages {s, s+cp, ...} as LOCAL table columns 0..P_l-1, so
    the caller passes `page_base` (int32 [P_l], scalar-prefetch): column j's
    first token position.  The masks become `page_base[j] + lane` where the
    single-device kernel uses `j*ps + lane` — at cp=1 with
    page_base[j] = j*ps they are the same arithmetic.

    (2) No `_finish` divide.  The kernel emits its raw online-softmax state
    — acc [b, hk, qr, d], m [b, hk, qr, 1], l [b, hk, qr, 1], all float32 —
    so shards can merge exactly:

        m*   = max_s m_s
        l*   = sum_s l_s * exp(m_s - m*)
        acc* = sum_s acc_s * exp(m_s - m*)
        out  = acc* / max(l*, eps)

    which is the SAME two-term merge the kernel itself applies page by page,
    just reassociated across shards (see `cp_softmax_combine`).  A shard
    whose every key is masked reports m = -inf, l = 0, acc = 0 and drops out
    of the sums; the round-robin layout puts sequence page 0 (token 0) on
    shard 0, so every active row has a finite global m.

    Passing `k_scale`/`v_scale` selects the int8 arena variant: page tiles
    dequantize in VMEM exactly as in `_fused_paged_decode_quant_forward`."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    quant = k_scale is not None
    b, sq, h, d = q.shape
    ps = arena_k.shape[1]
    hk = arena_k.shape[2]
    rep = h // hk
    P = tables.shape[1]
    R = rep * sq
    qr = -(-R // 8) * 8  # f32 sublane tile; pad rows are sliced off
    qt = jnp.transpose(q, (0, 2, 1, 3)).reshape(b, hk, rep, sq, d)
    qg = qt.reshape(b, hk, R, d)
    if qr != R:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, qr - R), (0, 0)))
    pos_v = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    tab = jnp.asarray(tables, jnp.int32).reshape(-1)
    base = jnp.asarray(page_base, jnp.int32).reshape(-1)

    def kernel(t_ref, base_ref, p_ref, q_ref, k_ref, v_ref, *rest):
        if quant:
            ks_ref, vs_ref, oa_ref, om_ref, ol_ref, m_scr, l_scr, acc_scr = rest
        else:
            oa_ref, om_ref, ol_ref, m_scr, l_scr, acc_scr = rest
        j = pl.program_id(2)
        n_p = pl.num_programs(2)
        p0 = p_ref[pl.program_id(0)]
        j0 = base_ref[j]

        @pl.when(j == 0)
        def _init():
            m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)

        # pages entirely beyond the newest visible position (window row
        # sq-1 sees up to pos + sq - 1) contribute nothing
        needed = j0 <= p0 + sq - 1

        @pl.when(needed)
        def _compute():
            if quant:
                qb = q_ref[...].astype(jnp.float32)
                kb = k_ref[...].astype(jnp.float32) * ks_ref[...]
                vb = v_ref[...].astype(jnp.float32) * vs_ref[...]
            else:
                qb = q_ref[...]  # [qr, d]
                kb = k_ref[...]  # [ps, d] — the page this table entry names
                vb = v_ref[...]
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [qr, ps]
            w = jax.lax.broadcasted_iota(jnp.int32, (qr, ps), 0) % sq
            jid = j0 + jax.lax.broadcasted_iota(jnp.int32, (qr, ps), 1)
            s = jnp.where((jid <= p0 + w) & (jid < max_len), s, _NEG_INF)
            m = m_scr[..., 0]
            l = l_scr[..., 0]
            m_new = jnp.maximum(m, s.max(-1))
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            m_scr[...] = m_new[..., None]
            l_scr[...] = (alpha * l + p.sum(-1))[..., None]
            pv = p if quant else p.astype(vb.dtype)
            acc_scr[...] = acc_scr[...] * alpha[..., None] + jax.lax.dot_general(
                pv, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        @pl.when(j == n_p - 1)
        def _emit():
            # partials out, UN-normalized: the cross-shard combine divides.
            # exp(m) can overflow where m is the -inf init of a fully masked
            # row; the combine's exp(m - m*) handles that, not us.
            oa_ref[...] = acc_scr[...]
            om_ref[...] = m_scr[...]
            ol_ref[...] = l_scr[...]

    page_tile = pl.BlockSpec(
        (None, ps, None, d), lambda s, g, j, t, bb, p: (t[s * P + j], 0, g, 0)
    )
    scale_tile = pl.BlockSpec(
        (None, ps, None, 1), lambda s, g, j, t, bb, p: (t[s * P + j], 0, g, 0)
    )
    q_tile = pl.BlockSpec(
        (None, None, qr, d), lambda s, g, j, t, bb, p: (s, g, 0, 0)
    )
    ml_tile = pl.BlockSpec(
        (None, None, qr, 1), lambda s, g, j, t, bb, p: (s, g, 0, 0)
    )
    in_specs = [q_tile, page_tile, page_tile]
    ins = [tab, base, pos_v, qg, arena_k, arena_v]
    if quant:
        in_specs += [scale_tile, scale_tile]
        ins += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, hk, P),
        in_specs=in_specs,
        out_specs=[q_tile, ml_tile, ml_tile],
        scratch_shapes=[
            pltpu.VMEM((qr, 1), jnp.float32),
            pltpu.VMEM((qr, 1), jnp.float32),
            pltpu.VMEM((qr, d), jnp.float32),
        ],
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, hk, qr, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hk, qr, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, hk, qr, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*ins)
    return acc, m, l


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _fused_paged_decode_partials(q, arena_k, arena_v, tables, page_base, pos,
                                 max_len, scale, interpret):
    """Differentiation-opaque wrapper over the partials kernel — same
    contract as `_fused_paged_decode` (decode is inference-only)."""
    return _fused_paged_decode_partials_forward(
        q, arena_k, arena_v, tables, page_base, pos, max_len, scale,
        interpret=interpret,
    )


def _fused_paged_decode_partials_fwd(q, arena_k, arena_v, tables, page_base,
                                     pos, max_len, scale, interpret):
    out = _fused_paged_decode_partials_forward(
        q, arena_k, arena_v, tables, page_base, pos, max_len, scale,
        interpret=interpret,
    )
    return out, None


def _fused_paged_decode_partials_bwd(max_len, scale, interpret, res, g):
    raise NotImplementedError(
        "context-parallel fused paged decode is inference-only (no backward)"
    )


_fused_paged_decode_partials.defvjp(
    _fused_paged_decode_partials_fwd, _fused_paged_decode_partials_bwd
)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10))
def _fused_paged_decode_partials_q8(q, arena_k, arena_v, k_scale, v_scale,
                                    tables, page_base, pos, max_len, scale,
                                    interpret):
    """Quantized partials kernel, differentiation-opaque (see above)."""
    return _fused_paged_decode_partials_forward(
        q, arena_k, arena_v, tables, page_base, pos, max_len, scale,
        interpret=interpret, k_scale=k_scale, v_scale=v_scale,
    )


def _fused_paged_decode_partials_q8_fwd(q, arena_k, arena_v, k_scale, v_scale,
                                        tables, page_base, pos, max_len,
                                        scale, interpret):
    out = _fused_paged_decode_partials_forward(
        q, arena_k, arena_v, tables, page_base, pos, max_len, scale,
        interpret=interpret, k_scale=k_scale, v_scale=v_scale,
    )
    return out, None


def _fused_paged_decode_partials_q8_bwd(max_len, scale, interpret, res, g):
    raise NotImplementedError(
        "context-parallel quantized fused paged decode is inference-only"
    )


_fused_paged_decode_partials_q8.defvjp(
    _fused_paged_decode_partials_q8_fwd, _fused_paged_decode_partials_q8_bwd
)


def cp_softmax_combine(acc, m, l, eps=1e-30):
    """Merge per-shard online-softmax partials into finished attention.

    Given shard partials acc_s = sum_j e^{s_j - m_s} v_j, m_s = max_j s_j,
    l_s = sum_j e^{s_j - m_s} over DISJOINT key sets (stacked on a leading
    shard axis, or pre-reduced by the caller):

        m*   = max_s m_s
        out  = (sum_s acc_s e^{m_s - m*}) / max(sum_s l_s e^{m_s - m*}, eps)

    — the flash-attention two-term merge reassociated across shards, so the
    result equals running one online softmax over the union of keys (up to
    float reassociation).  Fully masked shards (m_s = -inf, l_s = 0) drop
    out: e^{-inf - m*} = 0 for finite m*; the engine's round-robin page
    layout guarantees shard 0 sees token 0, keeping m* finite for every
    active row.  Pure jnp — usable both inside shard_map (after psum/pmax,
    pass the already-reduced sums with the max) and on stacked arrays in
    tests."""
    m_star = jnp.max(m, axis=0)
    corr = jnp.exp(m - m_star[None])
    l_star = jnp.sum(l * corr, axis=0)
    acc_star = jnp.sum(acc * corr, axis=0)
    return acc_star / jnp.maximum(l_star, eps)


def _fused_paged_decode_cp_impl(q, arena_k, arena_v, tables, pos, max_len,
                                scale, interpret, cp, mp, k_scale=None,
                                v_scale=None):
    """Context-parallel dispatch of the fused paged-decode kernel (ISSUE
    20): `shard_map` over ('cp', 'mp') with the ARENA PAGE axis block-split
    over 'cp' (shard s physically holds global pages [s*per_shard,
    (s+1)*per_shard)) and kv heads split over 'mp' exactly as in
    `_fused_paged_decode_tp`.  q, tables, and pos stay replicated across
    'cp'.

    Each shard derives its LOCAL view in-jit from the replicated global
    table: sequence page k lives on shard k % cp (the engine's round-robin
    allocator invariant), so shard s's columns are k = j*cp + s; a mapped
    global id g in its range becomes local row g - s*per_shard, anything
    else (unmapped 0-sentinel columns, other shards' pages never appear)
    redirects to local row 0 — that shard's own scratch page, whose garbage
    the position fence masks exactly as on one device.  `page_base[j] =
    (j*cp + s) * page_size` carries the true token positions into the
    kernel masks.  The per-shard partials then merge with ONE
    pmax + two psums over 'cp' (`cp_softmax_combine` math) — the only
    cross-device traffic the whole decode step adds."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..distributed import mesh as _mesh

    quant = k_scale is not None
    num_pages = arena_k.shape[0]
    per_shard = num_pages // cp
    ps = arena_k.shape[1]
    b, sq, h, d = q.shape
    hk = arena_k.shape[2]
    rep = h // hk
    R = rep * sq

    mp_ax = "mp" if mp > 1 else None
    heads = P(None, None, mp_ax, None)
    pages = P("cp", None, mp_ax, None)

    def body(qq, ak, av, ks, vs, t, p):
        s = jax.lax.axis_index("cp")
        Pl = t.shape[1] // cp
        cols = (s + cp * jnp.arange(Pl, dtype=jnp.int32)).astype(jnp.int32)
        g = jnp.take(t, cols, axis=1)  # [b, Pl] global page ids
        loc = g - s * per_shard
        loc = jnp.where((loc > 0) & (loc < per_shard), loc, 0).astype(jnp.int32)
        base = (cols * ps).astype(jnp.int32)
        if quant:
            acc, m, l = _fused_paged_decode_partials_q8(
                qq, ak, av, ks, vs, loc, base, p, max_len, scale, interpret
            )
        else:
            acc, m, l = _fused_paged_decode_partials(
                qq, ak, av, loc, base, p, max_len, scale, interpret
            )
        m_star = jax.lax.pmax(m, "cp")
        corr = jnp.exp(m - m_star)
        l_star = jax.lax.psum(l * corr, "cp")
        acc_star = jax.lax.psum(acc * corr, "cp")
        out = acc_star / jnp.maximum(l_star, 1e-30)  # [b, hk_l, qr, d] f32
        hk_l = out.shape[1]
        out = out[:, :, :R].reshape(b, hk_l, rep, sq, d)
        out = out.reshape(b, hk_l * rep, sq, d).astype(qq.dtype)
        return jnp.transpose(out, (0, 2, 1, 3))

    if not quant:
        # dummy replicated scalars keep ONE body signature for both modes
        k_scale = jnp.zeros((), jnp.float32)
        v_scale = jnp.zeros((), jnp.float32)
        scale_spec = P()
    else:
        scale_spec = pages
    fn = shard_map(
        body,
        mesh=_mesh.get_mesh(),
        in_specs=(heads, pages, pages, scale_spec, scale_spec,
                  P(None, None), P(None)),
        out_specs=heads,
        check_rep=False,
    )
    return fn(q, arena_k, arena_v, k_scale, v_scale, tables, pos)


# custom_vjp opacity, same contract as the single-device fused kernels: the
# cp combine's pmax/psum have no JAX differentiation rules, and decode is
# inference-only anyway — dispatch.apply's eager jax.vjp must be able to
# trace the forward without ever building a backward.
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _fused_paged_decode_cp(q, arena_k, arena_v, tables, pos, max_len, scale,
                           interpret, cp, mp):
    return _fused_paged_decode_cp_impl(
        q, arena_k, arena_v, tables, pos, max_len, scale, interpret, cp, mp
    )


def _fused_paged_decode_cp_fwd(q, arena_k, arena_v, tables, pos, max_len,
                               scale, interpret, cp, mp):
    return _fused_paged_decode_cp(
        q, arena_k, arena_v, tables, pos, max_len, scale, interpret, cp, mp
    ), None


def _fused_paged_decode_cp_bwd(max_len, scale, interpret, cp, mp, res, g):
    raise NotImplementedError(
        "context-parallel fused paged decode is inference-only"
    )


_fused_paged_decode_cp.defvjp(
    _fused_paged_decode_cp_fwd, _fused_paged_decode_cp_bwd
)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def _fused_paged_decode_cp_q8(q, arena_k, arena_v, k_scale, v_scale, tables,
                              pos, max_len, scale, interpret, cp, mp):
    return _fused_paged_decode_cp_impl(
        q, arena_k, arena_v, tables, pos, max_len, scale, interpret, cp, mp,
        k_scale=k_scale, v_scale=v_scale,
    )


def _fused_paged_decode_cp_q8_fwd(q, arena_k, arena_v, k_scale, v_scale,
                                  tables, pos, max_len, scale, interpret, cp,
                                  mp):
    return _fused_paged_decode_cp_q8(
        q, arena_k, arena_v, k_scale, v_scale, tables, pos, max_len, scale,
        interpret, cp, mp,
    ), None


def _fused_paged_decode_cp_q8_bwd(max_len, scale, interpret, cp, mp, res, g):
    raise NotImplementedError(
        "context-parallel quantized fused paged decode is inference-only"
    )


_fused_paged_decode_cp_q8.defvjp(
    _fused_paged_decode_cp_q8_fwd, _fused_paged_decode_cp_q8_bwd
)


def paged_decode_attention_array(q, arena_k, arena_v, tables, pos, max_len,
                                 scale=None, kernel="auto", k_scale=None,
                                 v_scale=None):
    """Paged-decode attention dispatcher.

    kernel="auto": the fused Pallas kernel when on TPU (or under interpret)
    and the shape is eligible, else gather-then-dense.  kernel="fused":
    require the fused kernel — raises ValueError when it cannot run (the
    engine surfaces this at construction, not mid-traffic).
    kernel="gather": force the gather-then-dense oracle (`paged_gather_kv`
    materializes each sequence's KV densely, then the exact dense-cache
    decode math runs on the result) — the bit-parity baseline the fused
    kernel is tested against.  Both paths are bit-identical to the dense
    slot pool given bit-identical cache rows.

    Under a tensor-parallel 'mp' mesh the fused kernel goes through
    `shard_map` (kv_heads axis sharded; see `_fused_paged_decode_tp`) and
    the gather oracle relies on GSPMD propagating the arena's heads
    sharding through the gather + dense einsums.

    k_scale/v_scale non-None selects the QUANTIZED paths (ISSUE 18): the
    arena holds int8 rows and the scale arenas hold their per-(row, kv
    head) float32 scales.  The fused kernel dequantizes per page tile in
    VMEM ('paged_decode_fused_q8'); the gather oracle gathers values and
    scales through the same tables and applies the identical
    `int8 * scale` dequant before the dense math, staying the parity
    baseline under quantization too."""
    if kernel not in ("auto", "fused", "gather"):
        raise ValueError(
            f"paged decode kernel must be auto|fused|gather, got {kernel!r}"
        )
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be given together")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    interpret = _FORCE_INTERPRET
    if kernel != "gather":
        from ..distributed import mesh as _mesh

        ok, reason = _fused_paged_viable(q, arena_k.shape[1])
        mp = _mesh.axis_size("mp")
        cp = _mesh.axis_size("cp")
        if ok and mp > 1 and (q.shape[2] % mp or arena_k.shape[2] % mp):
            # engine construction validates this for serving; direct callers
            # (or a q-head count that packs unevenly) fall back to the
            # GSPMD-sharded gather path instead of a shard_map shape error
            ok, reason = False, "paged heads not divisible by mp"
        if ok and cp > 1 and (tables.shape[1] % cp or arena_k.shape[0] % cp):
            # the engine pads pages_per_seq and the pool to cp multiples;
            # direct callers fall back to the GSPMD gather path
            ok, reason = False, "paged tables/pool not divisible by cp"
        on_path = _on_tpu() or interpret
        if ok and on_path:
            if cp > 1:
                _log_pallas_call("paged_decode_fused_cp_q8" if quant else
                                 "paged_decode_fused_cp")
                if quant:
                    return _fused_paged_decode_cp_q8(
                        q, arena_k, arena_v, k_scale, v_scale, tables, pos,
                        max_len, scale, interpret, cp, mp,
                    )
                return _fused_paged_decode_cp(
                    q, arena_k, arena_v, tables, pos, max_len, scale,
                    interpret, cp, mp,
                )
            _log_pallas_call("paged_decode_fused_q8" if quant else
                             "paged_decode_fused")
            if quant:
                if mp > 1:
                    return _fused_paged_decode_quant_tp(
                        q, arena_k, arena_v, k_scale, v_scale, tables, pos,
                        max_len, scale, interpret, mp,
                    )
                return _fused_paged_decode_quant(
                    q, arena_k, arena_v, k_scale, v_scale, tables, pos,
                    max_len, scale, interpret,
                )
            if mp > 1:
                return _fused_paged_decode_tp(
                    q, arena_k, arena_v, tables, pos, max_len, scale,
                    interpret, mp,
                )
            return _fused_paged_decode(
                q, arena_k, arena_v, tables, pos, max_len, scale, interpret
            )
        if kernel == "fused":
            raise ValueError(
                "paged decode kernel 'fused' unavailable: "
                + (reason or "not on TPU (tests set _FORCE_INTERPRET)")
            )
        if on_path:
            _log_pallas_fallback(reason, shape=q.shape)
    k = paged_gather_kv(arena_k, tables, max_len)
    v = paged_gather_kv(arena_v, tables, max_len)
    if quant:
        # the oracle's dequant is the same math the kernel runs in VMEM:
        # int8 rows * their gathered scale rows, q upcast to f32 so both
        # paths reduce at the same precision
        k = k.astype(jnp.float32) * paged_gather_kv(k_scale, tables, max_len)
        v = v.astype(jnp.float32) * paged_gather_kv(v_scale, tables, max_len)
        out = decode_attention_array(q.astype(jnp.float32), k, v, pos, scale)
        return out.astype(q.dtype)
    return decode_attention_array(q, k, v, pos, scale)


def paged_flash_decode(query, arena_k, arena_v, tables, pos, max_len, scale=None,
                       kernel="auto", k_scale=None, v_scale=None):
    """Tensor-level paged cached-decode attention.  `k_scale`/`v_scale`
    (the int8 arena's parallel scale buffers) select the quantized
    dispatch; the kv-quant mode string is deliberately a closure constant
    of the traced fn — ops.dispatch._code_key and the AOT snapshot
    fingerprint freeze closure values, so an executable cached under one
    quant mode can never serve the other even if avals were ever to
    coincide."""
    query, arena_k, arena_v = coerce(query), coerce(arena_k), coerce(arena_v)
    tables, pos = coerce(tables), coerce(pos)
    max_len = int(max_len)
    kernel = str(kernel)
    kv_quant = "int8" if k_scale is not None else "none"

    if kv_quant == "int8":
        k_scale, v_scale = coerce(k_scale), coerce(v_scale)

        def fq(q, ak, av, ks, vs, t, p):
            assert kv_quant == "int8"  # closure cell -> eager-cache key
            return paged_decode_attention_array(
                q, ak, av, t, p, max_len, scale, kernel=kernel,
                k_scale=ks, v_scale=vs,
            )

        return apply(
            fq, [query, arena_k, arena_v, k_scale, v_scale, tables, pos],
            name="paged_flash_decode_q8",
        )

    def f(q, ak, av, t, p):
        assert kv_quant == "none"  # closure cell -> eager-cache key
        return paged_decode_attention_array(
            q, ak, av, t, p, max_len, scale, kernel=kernel
        )

    return apply(f, [query, arena_k, arena_v, tables, pos], name="paged_flash_decode")


# ---------------------------------------------------------------------------
# Blockwise XLA fallback (O(seq) memory via scan + checkpoint)
# ---------------------------------------------------------------------------


def _blockwise_attention(q, k, v, mask, causal, scale, block_k=512):
    """q: [b, h, sq, d]; k,v: [b, h, sk, d]; mask broadcastable [b, h, sq, sk].

    Returns (out [b,h,sq,d] in q.dtype, lse [b,h,sq] f32)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if mask is not None or sk <= block_k or sk % block_k != 0:
        return _dense_attention(q, k, v, mask, causal, scale)

    nblocks = sk // block_k

    def body(carry, ki):
        m, l, acc = carry
        ks = lax.dynamic_slice_in_dim(k, ki * block_k, block_k, axis=2)
        vs = lax.dynamic_slice_in_dim(v, ki * block_k, block_k, axis=2)
        # bf16 operands, fp32 accumulation — full-rate MXU; scale applied to
        # the fp32 scores, not the half-precision operands
        s = jnp.einsum("bhqd,bhkd->bhqk", q, ks, preferred_element_type=jnp.float32) * scale
        if causal:
            q_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, block_k), 0)
            k_ids = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (sq, block_k), 1)
            s = jnp.where(q_ids >= k_ids - (sk - sq), s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v.dtype), vs, preferred_element_type=jnp.float32
        )
        return (m_new, l_new, acc_new), None

    init = (
        jnp.full((b, h, sq), _NEG_INF, jnp.float32),
        jnp.zeros((b, h, sq), jnp.float32),
        jnp.zeros((b, h, sq, d), jnp.float32),
    )
    (m, l, acc), _ = lax.scan(jax.checkpoint(body), init, jnp.arange(nblocks))
    l_safe = jnp.maximum(l, 1e-30)
    return (acc / l_safe[..., None]).astype(q.dtype), m + jnp.log(l_safe)


def _dense_attention(q, k, v, mask, causal, scale):
    # half-precision operands with fp32 accumulation (full-rate MXU); softmax
    # and masking in fp32.  Returns (out, lse).
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    sq, sk = q.shape[2], k.shape[2]
    if causal:
        q_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_ids >= k_ids - (sk - sq), s, _NEG_INF)
    if mask is not None:
        s = s + mask.astype(s.dtype)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    ).astype(q.dtype)
    return out, lse


def _flash_backward(q, k, v, mask, out, lse, g, causal, scale, block_k=512):
    """Explicit flash-attention-2 backward (dq, dk, dv), expressed for XLA.

    Matmul operands stay in the input (half) precision with fp32 accumulation
    — jax.vjp over the forward would instead produce fp32-operand matmuls
    (p and ds are fp32), halving MXU throughput and doubling HBM traffic
    (the round-1 AMP audit finding).  Reference capability:
    paddle/phi/kernels/gpu/flash_attn_grad_kernel.cu.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # [b,h,sq]

    if mask is not None or sk <= block_k or sk % block_k != 0:
        bk, nblocks = sk, 1
    else:
        bk, nblocks = block_k, sk // block_k

    def body(dq_acc, ki):
        k0 = ki * bk
        ks = lax.dynamic_slice_in_dim(k, k0, bk, axis=2)
        vs = lax.dynamic_slice_in_dim(v, k0, bk, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, ks, preferred_element_type=jnp.float32) * scale
        if causal:
            q_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, bk), 0)
            k_ids = k0 + jax.lax.broadcasted_iota(jnp.int32, (sq, bk), 1)
            s = jnp.where(q_ids >= k_ids - (sk - sq), s, _NEG_INF)
        if mask is not None:
            s = s + mask.astype(s.dtype)
        p = jnp.exp(s - lse[..., None])  # [b,h,sq,bk] f32
        pb = p.astype(q.dtype)
        dv_i = jnp.einsum(
            "bhqk,bhqd->bhkd", pb, g, preferred_element_type=jnp.float32
        ).astype(v.dtype)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g, vs, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[..., None]) * scale).astype(q.dtype)
        dq_acc = dq_acc + jnp.einsum(
            "bhqk,bhkd->bhqd", ds, ks, preferred_element_type=jnp.float32
        )
        dk_i = jnp.einsum(
            "bhqk,bhqd->bhkd", ds, q, preferred_element_type=jnp.float32
        ).astype(k.dtype)
        return dq_acc, (dk_i, dv_i)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    if nblocks == 1:
        dq, (dk, dv) = body(dq0, 0)
    else:
        dq, (dks, dvs) = lax.scan(jax.checkpoint(body), dq0, jnp.arange(nblocks))
        dk = jnp.moveaxis(dks, 0, 2).reshape(k.shape)
        dv = jnp.moveaxis(dvs, 0, 2).reshape(v.shape)
    return dq.astype(q.dtype), dk, dv


# ---------------------------------------------------------------------------
# public entry — jax-level (arrays in, arrays out; custom_vjp around pallas)
# ---------------------------------------------------------------------------

# Every Pallas kernel this module can dispatch, and every fallback reason it
# can emit — obs/metrics.py zero-renders both families so a fallback
# regression shows up as a counter MOVING, not a series appearing.  The two
# retired reasons ("seq not a 128-multiple", "attn_mask given") stay listed:
# their permanent zeros are the proof the gaps are closed.
_PALLAS_KERNELS = (
    "flash_fwd", "flash_bwd", "decode", "paged_decode_fused",
    "paged_decode_fused_q8", "paged_decode_fused_cp",
    "paged_decode_fused_cp_q8",
)
_FALLBACK_REASONS = (
    "attn_mask not key-padding",
    "q/k shapes differ",
    "head_dim > 256",
    "paged head_dim > 256",
    "paged page_size not 8-aligned",
    "paged heads not divisible by mp",
    "paged tables/pool not divisible by cp",
    "seq not a 128-multiple",  # retired (pad-and-mask) — must stay 0
    "attn_mask given",         # retired (key-bias lowering) — must stay 0
)

_fallback_lock = threading.Lock()
_fallback_logged = set()  # (reason, shape) pairs already warned about
_FALLBACK_LOG_BOUND = 512  # serving emits few distinct shapes; cap leaks


def _log_pallas_call(kernel):
    """Count a Pallas kernel dispatch (the positive counterpart to
    `_log_pallas_fallback`): benches and /metrics prove the fast path ran
    by this counter moving, not by the absence of fallbacks."""
    from .. import profiler as _prof

    _prof.record_flash_pallas_call(kernel)


def _log_pallas_fallback(reason, shape=None):
    """Gate honesty (round-1 finding): never silently run the slow path on a
    TPU — benches must be able to see which kernel they measured.  Counts
    every fallback into the profiler's `flash_fallbacks` gauge and warns
    once per (reason, q-shape) so a new shape hitting the slow path is
    visible even late in a long run."""
    from .. import profiler as _prof

    _prof.record_flash_fallback(reason)
    key = (reason, tuple(shape) if shape is not None else None)
    warn = False
    global _fallback_logged
    with _fallback_lock:
        if not isinstance(_fallback_logged, set):
            # tests plant falsy sentinels here to detect logging; keep their
            # `assert not fa._fallback_logged` semantics by replacing the
            # sentinel with a real (truthy) set instead of crashing
            _fallback_logged = set()
        if key not in _fallback_logged:
            if len(_fallback_logged) >= _FALLBACK_LOG_BOUND:
                _fallback_logged.clear()
            _fallback_logged.add(key)
            warn = True
    if warn:
        import logging

        logging.getLogger("paddle_tpu").warning(
            "flash_attention: Pallas kernel unavailable (%s) for q shape %s; "
            "using XLA blockwise fallback",
            reason, key[1],
        )


# tests set this to exercise the Pallas kernels off-TPU via interpret mode
_FORCE_INTERPRET = False


def _key_padding_bias(mask, b, sk):
    """If `mask` is a plain key-padding mask — additive, broadcast over the
    q rows and heads, i.e. shape [mb, 1, 1, sk] with mb in {1, b} — lower it
    to a [b, sk] f32 per-key bias the Pallas kernels add in-kernel.  Any
    other mask geometry returns None (those stay on the XLA fallback)."""
    if mask is None:
        return None
    if mask.ndim != 4 or mask.shape[1] != 1 or mask.shape[2] != 1:
        return None
    mb = mask.shape[0]
    if mb not in (1, b) or mask.shape[3] != sk:
        return None
    return jnp.broadcast_to(
        mask.reshape(mb, sk).astype(jnp.float32), (b, sk)
    )


def _pad_flash_inputs(q, k, v, segments, kbias):
    """Pad the sequence dim of [b,h,s,d] q/k/v up to the next 128 multiple
    so the Pallas kernels' block geometry holds on ragged serving shapes.
    Padded positions MUST be fenced or they poison real rows' softmax
    denominators (a zero-key column scores 0, not -inf) — so the pad path
    always carries segment ids: real positions keep their ids (or 0 when
    the caller had none), pad positions get -1 and are masked against
    everything real.  kbias pads with 0 (pad columns are already fenced by
    the segment ids).  Returns (q, k, v, segments, kbias, s_pad)."""
    b, h, s, d = q.shape
    s_pad = -(-s // 128) * 128
    if s_pad == s:
        return q, k, v, segments, kbias, s
    pad = s_pad - s
    q = jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0)))
    k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
    v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    if segments is None:
        segments = jnp.zeros((b, s), jnp.int32)
    segments = jnp.pad(
        jnp.asarray(segments, jnp.int32), ((0, 0), (0, pad)),
        constant_values=-1,
    )
    if kbias is not None:
        kbias = jnp.pad(kbias, ((0, 0), (0, pad)))
    return q, k, v, segments, kbias, s_pad


def _pallas_viable(q, k, mask, kbias):
    """Static eligibility for the dense Pallas kernels.  Non-128-multiple
    sequences are no longer refused (the wrapper pads and fences them) and
    plain key-padding masks lower to an in-kernel bias — the remaining
    reasons are structural."""
    d = q.shape[3]
    if mask is not None and kbias is None:
        return False, "attn_mask not key-padding"
    if q.shape != k.shape:
        return False, "q/k shapes differ"
    if d > 256:
        return False, "head_dim > 256"
    return True, None


def _segments_mask(segments, b, h):
    """[b, s] segment ids -> additive [b, 1, s, s] mask for the XLA paths."""
    eq = segments[:, None, :, None] == segments[:, None, None, :]
    return jnp.where(eq, 0.0, _NEG_INF).astype(jnp.float32)


def _seg_flat(segments, h):
    """[b, s] -> [b, s, 1] int32 for the Pallas kernels (the kernels' seg
    BlockSpecs divide the bh grid coordinate by n_heads, so no per-head
    broadcast is materialized)."""
    return segments[:, :, None].astype(jnp.int32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _flash_attention_core(q, k, v, mask, segments, causal, scale):
    out, _, _ = _flash_fwd_impl(q, k, v, mask, segments, causal, scale)
    return out


def _flash_fwd_impl(q, k, v, mask, segments, causal, scale):
    """q,k,v: [b, h, s, d] → (out, lse [b,h,s], used_pallas)."""
    b, h, s, d = q.shape
    interpret = _FORCE_INTERPRET
    if _on_tpu() or interpret:
        kbias = _key_padding_bias(mask, b, k.shape[2])
        ok, reason = _pallas_viable(q, k, mask, kbias)
        if ok:
            qp, kp, vp, segp, kbp, s_pad = _pad_flash_inputs(
                q, k, v, segments, kbias
            )
            _log_pallas_call("flash_fwd")
            qf = qp.reshape(b * h, s_pad, d)
            kf = kp.reshape(b * h, s_pad, d)
            vf = vp.reshape(b * h, s_pad, d)
            segf = _seg_flat(segp, h) if segp is not None else None
            kbf = kbp[:, :, None] if kbp is not None else None
            out, lse = _pallas_flash_forward(
                qf, kf, vf, causal, scale, segments=segf, n_heads=h,
                interpret=interpret, kbias=kbf,
            )
            out = out.reshape(b, h, s_pad, d)[:, :, :s]
            lse = lse.reshape(b, h, s_pad)[:, :, :s]
            return out, lse, True
        _log_pallas_fallback(reason, shape=q.shape)
    if segments is not None:
        seg_mask = _segments_mask(segments, b, h)
        mask = seg_mask if mask is None else mask + seg_mask
    out, lse = _blockwise_attention(q, k, v, mask, causal, scale)
    return out, lse, False


def _flash_fwd_rule(q, k, v, mask, segments, causal, scale):
    out, lse, used_pallas = _flash_fwd_impl(q, k, v, mask, segments, causal, scale)
    return out, (q, k, v, mask, segments, out, lse, used_pallas)


def _flash_bwd_rule(causal, scale, res, g):
    q, k, v, mask, segments, out, lse, used_pallas = res
    if used_pallas:
        b, h, s, d = q.shape
        # reconstruct the forward's padded geometry deterministically; pad
        # g/out/lse with zeros — a padded q row's p is either 0 (masked vs
        # real keys) or hits g=0/delta=0, so it contributes exactly nothing
        # to dk/dv, and its own dq row is sliced off
        kbias = _key_padding_bias(mask, b, k.shape[2])
        qp, kp, vp, segp, kbp, s_pad = _pad_flash_inputs(q, k, v, segments, kbias)
        gp, outp, lsep = g, out, lse
        if s_pad != s:
            pad = s_pad - s
            gp = jnp.pad(g, ((0, 0), (0, 0), (0, pad), (0, 0)))
            outp = jnp.pad(out, ((0, 0), (0, 0), (0, pad), (0, 0)))
            lsep = jnp.pad(lse, ((0, 0), (0, 0), (0, pad)))
        segf = _seg_flat(segp, h) if segp is not None else None
        kbf = kbp[:, :, None] if kbp is not None else None
        _log_pallas_call("flash_bwd")
        dq, dk, dv = _pallas_flash_backward(
            qp.reshape(b * h, s_pad, d),
            kp.reshape(b * h, s_pad, d),
            vp.reshape(b * h, s_pad, d),
            gp.reshape(b * h, s_pad, d),
            outp.reshape(b * h, s_pad, d),
            lsep.reshape(b * h, s_pad, 1),
            causal,
            scale,
            segments=segf,
            n_heads=h,
            interpret=_FORCE_INTERPRET,
            kbias=kbf,
        )
        return (
            dq.reshape(b, h, s_pad, d)[:, :, :s],
            dk.reshape(b, h, s_pad, d)[:, :, :s],
            dv.reshape(b, h, s_pad, d)[:, :, :s],
            None,
            None,
        )
    if segments is not None:
        seg_mask = _segments_mask(segments, q.shape[0], q.shape[1])
        mask = seg_mask if mask is None else mask + seg_mask
    dq, dk, dv = _flash_backward(q, k, v, mask, out, lse, g, causal, scale)
    return dq, dk, dv, None, None


_flash_attention_core.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def sdpa_array(q, k, v, mask=None, causal=False, scale=None, segment_ids=None):
    """Array-level SDPA used by models and by the Tensor-level op below.

    q,k,v: [batch, seq, heads, dim] → out [batch, seq, heads, dim].
    segment_ids: optional [batch, seq] int — attention is confined to
    positions with equal ids (packed-sequence / varlen semantics).
    """
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # grouped-query attention: expand kv heads if fewer than q heads
    hq, hk = qt.shape[1], kt.shape[1]
    if hk != hq:
        rep = hq // hk
        kt = jnp.repeat(kt, rep, axis=1)
        vt = jnp.repeat(vt, rep, axis=1)
    out = _flash_attention_core(qt, kt, vt, mask, segment_ids, causal, scale)
    return jnp.transpose(out, (0, 2, 1, 3))


def cu_seqlens_to_segment_ids(cu_seqlens, total_len):
    """[n+1] cumulative lengths -> [total_len] segment ids (padding tail,
    if any, lands in the last registered segment's id + 1 region and is
    masked against everything by construction)."""
    pos = jnp.arange(total_len, dtype=jnp.int32)
    return jnp.searchsorted(jnp.asarray(cu_seqlens, jnp.int32)[1:], pos, side="right")


def flash_attn_varlen_array(q, k, v, cu_seqlens, causal=True, scale=None):
    """Packed varlen attention (reference: phi flash_attn_varlen /
    flash_attn_unpadded, paddle/phi/kernels/gpu/flash_attn_kernel.cu).

    q,k,v: [total, heads, dim] — sequences packed along dim 0;
    cu_seqlens: [n+1] int with cu[0]==0, cu[-1]<=total.  TPU-native: the
    packed layout + segment-id masking keeps shapes static for XLA.
    """
    total = q.shape[0]
    seg = cu_seqlens_to_segment_ids(cu_seqlens, total)[None, :]  # [1, total]
    out = sdpa_array(
        q[None], k[None], v[None], None, causal, scale, segment_ids=seg
    )
    return out[0]


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True,
    segment_ids=None,
):
    """segment_ids: optional [b, s] int Tensor — packed-sequence / padding
    masking that KEEPS the Pallas kernel eligible (an additive attn_mask
    forces the XLA fallback; models with plain key-padding masks should
    pass segment ids instead — see models/bert.py)."""
    query, key, value = coerce(query), coerce(key), coerce(value)
    ins = [query, key, value]
    has_mask = attn_mask is not None
    if has_mask:
        mask = coerce(attn_mask)
        if mask.dtype == "bool":
            from . import cast as _  # noqa

            mask = apply(
                lambda m: jnp.where(m, 0.0, _NEG_INF).astype(jnp.float32), [mask]
            )
        ins.append(mask)
    has_segs = segment_ids is not None
    if has_segs:
        ins.append(coerce(segment_ids))

    def f(q, k, v, *rest):
        m = rest[0] if has_mask else None
        segs = rest[-1] if has_segs else None
        return sdpa_array(q, k, v, m, is_causal, segment_ids=segs)

    out = apply(f, ins, name="flash_attention")
    if dropout_p > 0.0 and training:
        from ..nn.functional import dropout as _dropout

        out = _dropout(out, dropout_p, training=training)
    return out


def flash_attn_varlen(query, key, value, cu_seqlens_q, cu_seqlens_k=None, causal=True, scale=None):
    """Tensor-level varlen entry (reference: paddle flash_attn_unpadded).
    Only self-attention layouts (shared cu_seqlens) are supported."""
    query, key, value = coerce(query), coerce(key), coerce(value)
    cu = coerce(cu_seqlens_q)
    if cu_seqlens_k is not None and cu_seqlens_k is not cu_seqlens_q:
        cu_k = coerce(cu_seqlens_k)
        traced = isinstance(cu._raw, jax.core.Tracer) or isinstance(
            cu_k._raw, jax.core.Tracer
        )
        if traced:
            # values can't be compared under tracing, and trusting a shape
            # match would silently mis-compute cross-attention layouts —
            # require the SAME object (or omit cu_seqlens_k) inside traced
            # code; only self-attention layouts are supported either way
            raise NotImplementedError(
                "flash_attn_varlen: cu_seqlens_k equality cannot be "
                "verified under @to_static tracing; pass cu_seqlens_k as "
                "the same tensor object as cu_seqlens_q (or omit it) — "
                "only self-attention layouts are supported"
            )
        else:
            same = cu_k._raw.shape == cu._raw.shape and bool(
                (cu_k._raw == cu._raw).all()
            )
            if not same:
                raise NotImplementedError(
                    "flash_attn_varlen: distinct cu_seqlens_k is not supported "
                    "(self-attention layouts only); pass equal cu_seqlens"
                )

    def f(q, k, v, cq):
        return flash_attn_varlen_array(q, k, v, cq, causal, scale)

    return apply(f, [query, key, value, cu], name="flash_attn_varlen")
