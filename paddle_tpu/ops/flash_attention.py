"""Flash attention — TPU-native (reference capability:
paddle/phi/kernels/gpu/flash_attn_kernel.cu wrapping the FlashAttention CUDA
library; here a Pallas TPU kernel + an XLA blockwise fallback).

Layout convention follows the reference API: [batch, seq, num_heads, head_dim].

Design (see /opt/skills/guides/pallas_guide.md):
- forward: online-softmax blockwise kernel; grid over (batch*heads, q blocks);
  K/V streamed through VMEM; causal masking applied per block.
- backward: blockwise recompute (flash-attention-2 style) expressed in JAX —
  XLA fuses it well on TPU; a hand-written Pallas backward is a later
  optimization.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..framework import core as _core
from ..tensor import Tensor
from .dispatch import apply, coerce

_NEG_INF = -1e30


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale, block_q, block_k, seq_len):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[...]  # [block_q, d] — keep half precision for the MXU

    m = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    num_k_blocks = seq_len // block_k
    q_start = qi * block_q

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(ki * block_k, block_k), :]
        v = v_ref[pl.ds(ki * block_k, block_k), :]
        # bf16 operands, fp32 accumulate; scale folded into the fp32 scores
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_ids = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(-1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    upper = (q_start + block_q + block_k - 1) // block_k if causal else num_k_blocks
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m, l, acc))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[...] = (m + jnp.log(l_safe))[:, None]


def _pallas_flash_forward(q, k, v, causal, scale, block_q=512, block_k=512):
    """q,k,v: [bh, seq, d] — returns (out [bh, seq, d], lse [bh, seq] f32)."""
    from jax.experimental import pallas as pl

    bh, seq_len, d = q.shape
    # block sizes must divide the sequence (the grid/fori_loop floor-divide
    # would otherwise silently skip trailing q rows / k blocks, e.g. s=640
    # with block 512); the caller guarantees s % 128 == 0, so 128 always works
    block_q = next(b for b in (block_q, 256, 128) if seq_len % b == 0 and b <= seq_len)
    block_k = next(b for b in (block_k, 256, 128) if seq_len % b == 0 and b <= seq_len)
    grid = (bh, seq_len // block_q)

    kernel = functools.partial(
        _flash_fwd_kernel,
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        seq_len=seq_len,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, seq_len, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            # [bh, seq, 1] — a trailing unit dim keeps the block TPU-tileable
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_len, 1), jnp.float32),
        ],
    )(q, k, v)


# ---------------------------------------------------------------------------
# Blockwise XLA fallback (O(seq) memory via scan + checkpoint)
# ---------------------------------------------------------------------------


def _blockwise_attention(q, k, v, mask, causal, scale, block_k=512):
    """q: [b, h, sq, d]; k,v: [b, h, sk, d]; mask broadcastable [b, h, sq, sk].

    Returns (out [b,h,sq,d] in q.dtype, lse [b,h,sq] f32)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if mask is not None or sk <= block_k or sk % block_k != 0:
        return _dense_attention(q, k, v, mask, causal, scale)

    nblocks = sk // block_k

    def body(carry, ki):
        m, l, acc = carry
        ks = lax.dynamic_slice_in_dim(k, ki * block_k, block_k, axis=2)
        vs = lax.dynamic_slice_in_dim(v, ki * block_k, block_k, axis=2)
        # bf16 operands, fp32 accumulation — full-rate MXU; scale applied to
        # the fp32 scores, not the half-precision operands
        s = jnp.einsum("bhqd,bhkd->bhqk", q, ks, preferred_element_type=jnp.float32) * scale
        if causal:
            q_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, block_k), 0)
            k_ids = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (sq, block_k), 1)
            s = jnp.where(q_ids >= k_ids - (sk - sq), s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v.dtype), vs, preferred_element_type=jnp.float32
        )
        return (m_new, l_new, acc_new), None

    init = (
        jnp.full((b, h, sq), _NEG_INF, jnp.float32),
        jnp.zeros((b, h, sq), jnp.float32),
        jnp.zeros((b, h, sq, d), jnp.float32),
    )
    (m, l, acc), _ = lax.scan(jax.checkpoint(body), init, jnp.arange(nblocks))
    l_safe = jnp.maximum(l, 1e-30)
    return (acc / l_safe[..., None]).astype(q.dtype), m + jnp.log(l_safe)


def _dense_attention(q, k, v, mask, causal, scale):
    # half-precision operands with fp32 accumulation (full-rate MXU); softmax
    # and masking in fp32.  Returns (out, lse).
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    sq, sk = q.shape[2], k.shape[2]
    if causal:
        q_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_ids >= k_ids - (sk - sq), s, _NEG_INF)
    if mask is not None:
        s = s + mask.astype(s.dtype)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    ).astype(q.dtype)
    return out, lse


def _flash_backward(q, k, v, mask, out, lse, g, causal, scale, block_k=512):
    """Explicit flash-attention-2 backward (dq, dk, dv), expressed for XLA.

    Matmul operands stay in the input (half) precision with fp32 accumulation
    — jax.vjp over the forward would instead produce fp32-operand matmuls
    (p and ds are fp32), halving MXU throughput and doubling HBM traffic
    (the round-1 AMP audit finding).  Reference capability:
    paddle/phi/kernels/gpu/flash_attn_grad_kernel.cu.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # [b,h,sq]

    if mask is not None or sk <= block_k or sk % block_k != 0:
        bk, nblocks = sk, 1
    else:
        bk, nblocks = block_k, sk // block_k

    def body(dq_acc, ki):
        k0 = ki * bk
        ks = lax.dynamic_slice_in_dim(k, k0, bk, axis=2)
        vs = lax.dynamic_slice_in_dim(v, k0, bk, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, ks, preferred_element_type=jnp.float32) * scale
        if causal:
            q_ids = jax.lax.broadcasted_iota(jnp.int32, (sq, bk), 0)
            k_ids = k0 + jax.lax.broadcasted_iota(jnp.int32, (sq, bk), 1)
            s = jnp.where(q_ids >= k_ids - (sk - sq), s, _NEG_INF)
        if mask is not None:
            s = s + mask.astype(s.dtype)
        p = jnp.exp(s - lse[..., None])  # [b,h,sq,bk] f32
        pb = p.astype(q.dtype)
        dv_i = jnp.einsum(
            "bhqk,bhqd->bhkd", pb, g, preferred_element_type=jnp.float32
        ).astype(v.dtype)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g, vs, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[..., None]) * scale).astype(q.dtype)
        dq_acc = dq_acc + jnp.einsum(
            "bhqk,bhkd->bhqd", ds, ks, preferred_element_type=jnp.float32
        )
        dk_i = jnp.einsum(
            "bhqk,bhqd->bhkd", ds, q, preferred_element_type=jnp.float32
        ).astype(k.dtype)
        return dq_acc, (dk_i, dv_i)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    if nblocks == 1:
        dq, (dk, dv) = body(dq0, 0)
    else:
        dq, (dks, dvs) = lax.scan(jax.checkpoint(body), dq0, jnp.arange(nblocks))
        dk = jnp.moveaxis(dks, 0, 2).reshape(k.shape)
        dv = jnp.moveaxis(dvs, 0, 2).reshape(v.shape)
    return dq.astype(q.dtype), dk, dv


# ---------------------------------------------------------------------------
# public entry — jax-level (arrays in, arrays out; custom_vjp around pallas)
# ---------------------------------------------------------------------------

_fallback_logged = False


def _log_pallas_fallback(reason):
    """Gate honesty (round-1 finding): never silently run the slow path on a
    TPU — benches must be able to see which kernel they measured."""
    global _fallback_logged
    if not _fallback_logged:
        import logging

        logging.getLogger("paddle_tpu").warning(
            "flash_attention: Pallas kernel unavailable (%s); using XLA blockwise fallback",
            reason,
        )
        _fallback_logged = True


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_attention_core(q, k, v, mask, causal, scale):
    out, _ = _flash_fwd_impl(q, k, v, mask, causal, scale)
    return out


def _flash_fwd_impl(q, k, v, mask, causal, scale):
    """q,k,v: [b, h, s, d] → (out, lse)."""
    b, h, s, d = q.shape
    if _on_tpu():
        if mask is not None:
            _log_pallas_fallback("attn_mask given")
        elif s % 128 != 0 or q.shape != k.shape:
            _log_pallas_fallback(f"seq {s} not a 128-multiple or q/k shapes differ")
        elif d > 256:
            _log_pallas_fallback(f"head_dim {d} > 256")
        else:
            qf = q.reshape(b * h, s, d)
            kf = k.reshape(b * h, s, d)
            vf = v.reshape(b * h, s, d)
            out, lse = _pallas_flash_forward(qf, kf, vf, causal, scale)
            return out.reshape(b, h, s, d), lse.reshape(b, h, s)  # lse [bh,s,1]
    return _blockwise_attention(q, k, v, mask, causal, scale)


def _flash_fwd_rule(q, k, v, mask, causal, scale):
    out, lse = _flash_fwd_impl(q, k, v, mask, causal, scale)
    return out, (q, k, v, mask, out, lse)


def _flash_bwd_rule(causal, scale, res, g):
    q, k, v, mask, out, lse = res
    dq, dk, dv = _flash_backward(q, k, v, mask, out, lse, g, causal, scale)
    return dq, dk, dv, None


_flash_attention_core.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def sdpa_array(q, k, v, mask=None, causal=False, scale=None):
    """Array-level SDPA used by models and by the Tensor-level op below.

    q,k,v: [batch, seq, heads, dim] → out [batch, seq, heads, dim].
    """
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # grouped-query attention: expand kv heads if fewer than q heads
    hq, hk = qt.shape[1], kt.shape[1]
    if hk != hq:
        rep = hq // hk
        kt = jnp.repeat(kt, rep, axis=1)
        vt = jnp.repeat(vt, rep, axis=1)
    out = _flash_attention_core(qt, kt, vt, mask, causal, scale)
    return jnp.transpose(out, (0, 2, 1, 3))


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True
):
    query, key, value = coerce(query), coerce(key), coerce(value)
    ins = [query, key, value]
    has_mask = attn_mask is not None
    if has_mask:
        mask = coerce(attn_mask)
        if mask.dtype == "bool":
            from . import cast as _  # noqa

            mask = apply(
                lambda m: jnp.where(m, 0.0, _NEG_INF).astype(jnp.float32), [mask]
            )
        ins.append(mask)

    def f(q, k, v, *m):
        return sdpa_array(q, k, v, m[0] if m else None, is_causal)

    out = apply(f, ins, name="flash_attention")
    if dropout_p > 0.0 and training:
        from ..nn.functional import dropout as _dropout

        out = _dropout(out, dropout_p, training=training)
    return out
