"""Search/sort ops (reference: python/paddle/tensor/search.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .dispatch import apply, coerce


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = coerce(x)

    def f(a):
        if axis is None:
            r = jnp.argmax(a.reshape(-1))
            return r.reshape((1,) * a.ndim) if keepdim else r
        return jnp.argmax(a, axis=axis, keepdims=keepdim)

    return apply(f, [x], name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = coerce(x)

    def f(a):
        if axis is None:
            r = jnp.argmin(a.reshape(-1))
            return r.reshape((1,) * a.ndim) if keepdim else r
        return jnp.argmin(a, axis=axis, keepdims=keepdim)

    return apply(f, [x], name="argmin")


def argsort(x, axis=-1, descending=False, stable=True, name=None):
    x = coerce(x)

    def f(a):
        idx = jnp.argsort(a, axis=axis, stable=stable, descending=descending)
        return idx

    return apply(f, [x], name="argsort")


def sort(x, axis=-1, descending=False, stable=True, name=None):
    x = coerce(x)

    def f(a):
        s = jnp.sort(a, axis=axis, stable=stable, descending=descending)
        return s

    return apply(f, [x], name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = coerce(x)
    if isinstance(k, Tensor):
        k = int(k.numpy())
    ax = axis if axis is not None else -1

    def f(a):
        a2 = jnp.moveaxis(a, ax, -1)
        if largest:
            v, i = jax.lax.top_k(a2, k)
        else:
            v, i = jax.lax.top_k(-a2, k)
            v = -v
        return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i, -1, ax)

    vals, idx = apply(f, [x], multi=True, name="topk")
    return vals, idx


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = coerce(x)

    def f(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis)
        v = jnp.take(s, k - 1, axis=axis)
        ii = jnp.take(i, k - 1, axis=axis)
        if keepdim:
            v = jnp.expand_dims(v, axis)
            ii = jnp.expand_dims(ii, axis)
        return v, ii

    return apply(f, [x], multi=True, name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    x = coerce(x)

    def f(a):
        s = jnp.sort(a, axis=axis)
        s2 = jnp.moveaxis(s, axis, -1)
        eq = s2[..., :, None] == s2[..., None, :]
        cnt = eq.sum(-1)
        best = jnp.argmax(cnt, -1)
        v = jnp.take_along_axis(s2, best[..., None], -1)[..., 0]
        idx = jnp.argmax(jnp.moveaxis(a, axis, -1) == v[..., None], -1)
        if keepdim:
            v = jnp.expand_dims(v, axis)
            idx = jnp.expand_dims(idx, axis)
        return v, idx

    return apply(f, [x], multi=True, name="mode")


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    ss, v = coerce(sorted_sequence), coerce(values)
    side = "right" if right else "left"
    return apply(
        lambda a, b: jnp.searchsorted(a, b, side=side).astype(jnp.int32 if out_int32 else jnp.int64),
        [ss, v],
        name="searchsorted",
    )


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)
