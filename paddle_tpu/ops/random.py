"""Random ops (reference: python/paddle/tensor/random.py).

All sampling threads through the global Generator's key (see
framework/random.py), so randomness is reproducible under `paddle.seed` and
correctly becomes threaded state inside @to_static-compiled steps.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import core as _core
from ..framework.random import default_generator
from ..tensor import Tensor
from .creation import _dt, _shape_list
from .dispatch import apply, coerce, wrap, inplace_rebind


def _key():
    return default_generator.next_key()


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    shape = _shape_list(shape)
    dt = _dt(dtype)
    key = _key()
    return wrap(jax.random.uniform(key, shape, dt, minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    out = uniform(x.shape, x.dtype, min, max)
    return inplace_rebind(x, out)


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype=None, name=None):
    shape = _shape_list(shape)
    return wrap(jax.random.normal(_key(), shape, _dt(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        mean_t = coerce(mean)
        std_t = coerce(std)
        sh = tuple(np.broadcast_shapes(tuple(mean_t.shape), tuple(std_t.shape)))
        key = _key()
        return apply(
            lambda m, s: m + s * jax.random.normal(key, sh, m.dtype),
            [mean_t, std_t],
            name="normal",
        )
    shape = _shape_list(shape if shape is not None else [1])
    return wrap(mean + std * jax.random.normal(_key(), shape, _dt(None)))


def normal_(x, mean=0.0, std=1.0, name=None):
    return inplace_rebind(x, coerce(normal(mean, std, x.shape)).astype(x.dtype))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    shape = _shape_list(shape)
    return wrap(mean + std * jax.random.normal(_key(), shape, _dt(dtype)))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    shape = _shape_list(shape)
    return wrap(jax.random.randint(_key(), shape, low, high, _dt(dtype, "int64")))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = coerce(x)
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    return wrap(jax.random.permutation(_key(), int(n)).astype(_dt(dtype, "int64")))


def shuffle(x, axis=0, name=None):
    x = coerce(x)
    key = _key()
    return apply(lambda a: jax.random.permutation(key, a, axis=axis), [x], name="shuffle")


def bernoulli(x, name=None):
    x = coerce(x)
    key = _key()
    return apply(
        lambda p: jax.random.bernoulli(key, p).astype(p.dtype), [x], name="bernoulli"
    )


def bernoulli_(x, p=0.5, name=None):
    key = _key()
    out = apply(
        lambda a: jax.random.bernoulli(key, p, a.shape).astype(a.dtype),
        [coerce(x)],
        name="bernoulli_",
    )
    return inplace_rebind(x, out)


def poisson(x, name=None):
    x = coerce(x)
    key = _key()
    return apply(lambda lam: jax.random.poisson(key, lam).astype(lam.dtype), [x], name="poisson")


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = coerce(x)
    key = _key()

    def f(p):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        if replacement:
            return jax.random.categorical(key, logits, axis=-1, shape=(
                (p.shape[0], num_samples) if p.ndim == 2 else (num_samples,)
            ) if p.ndim == 2 else (num_samples,))
        # without replacement: gumbel top-k
        g = jax.random.gumbel(key, p.shape, p.dtype)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx

    if x.ndim == 2 and replacement:
        def f2(p):
            logits = jnp.log(jnp.maximum(p, 1e-30))
            return jax.random.categorical(key, logits, axis=-1, shape=(num_samples, p.shape[0])).T
        return apply(f2, [x.detach()], name="multinomial")
    return apply(f, [x.detach()], name="multinomial")


def exponential_(x, lam=1.0, name=None):
    key = _key()
    out = apply(
        lambda a: (jax.random.exponential(key, a.shape, a.dtype) / lam).astype(a.dtype),
        [coerce(x)],
        name="exponential_",
    )
    return inplace_rebind(x, out)
