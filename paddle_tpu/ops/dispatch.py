"""Op dispatcher — the single Python→XLA boundary.

Replaces the reference's kernel dispatch stack (phi::KernelFactory selection +
generated ad_funcs, SURVEY.md §3.1): every framework op is a jax-traceable
function over arrays; `apply()` executes it (eagerly via jax's op cache, or
symbolically under @to_static tracing) and, when autograd is live, records one
GradNode whose VJP comes from `jax.vjp`.  AMP O1 casting hooks in here too
(reference: paddle/fluid/eager/amp_utils.h).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import core as _core


def _is_inexact(arr):
    return jnp.issubdtype(jnp.dtype(arr.dtype), jnp.inexact)


# ---------------------------------------------------------------------------
# Eager fast path: cached jitted forward(+VJP) executables.
#
# SURVEY §7 "hard parts": per-op dispatch overhead.  A fresh `jax.vjp`
# retrace per eager op costs ~ms of Python; here each (op code, closure
# values, input avals) maps to ONE jitted executable returning
# (outs, vjp_fn) — jax.vjp's vjp_fn is a pytree (Partial over residual
# arrays), so it crosses the jit boundary, and one shared jitted applier
# runs it at backward time.  Ops whose closures capture arrays/Tensors (or
# anything we can't hash by value) skip the cache and take the retrace
# path.  This mirrors the reference's cached ad_funcs + KernelFactory
# lookup (paddle/fluid/eager/api/generated) in spirit: dispatch becomes a
# dictionary hit.
# ---------------------------------------------------------------------------

import collections as _collections

_UNHASHABLE = object()
_SIMPLE_TYPES = (int, float, bool, str, bytes, type(None))
_EAGER_CACHE = _collections.OrderedDict()
_EAGER_STATS = {"hits": 0, "misses": 0, "evictions": 0, "invalidations": 0}
_BWD_APPLY = None


def _eager_cache_cap():
    """LRU bound on the executable cache (FLAGS_eager_cache_max_entries) —
    long-running multi-model processes must not grow it without bound."""
    try:
        cap = int(_core.flag("FLAGS_eager_cache_max_entries"))
    except (KeyError, TypeError, ValueError):
        cap = 4096
    return max(1, cap)


def cache_stats():
    """Eager executable cache counters for jit.cache_info()."""
    return {
        "entries": len(_EAGER_CACHE),
        "capacity": _eager_cache_cap(),
        **_EAGER_STATS,
    }


def _freeze(v, depth=0):
    """Value -> hashable key component, or _UNHASHABLE."""
    if depth > 6:
        return _UNHASHABLE
    if isinstance(v, _SIMPLE_TYPES):
        # type matters: 1 == 1.0 == True hash equal, but bake into an
        # executable differently (dtype promotion)
        return (type(v).__name__, v)
    from ..tensor import Tensor

    if isinstance(v, (Tensor, jax.Array)) or type(v).__module__ == "numpy":
        return _UNHASHABLE  # mutable-by-rebind / array values: never key
    if isinstance(v, (tuple, list)):
        items = tuple(_freeze(x, depth + 1) for x in v)
        if any(i is _UNHASHABLE for i in items):
            return _UNHASHABLE
        return (type(v).__name__, items)
    if isinstance(v, dict):
        try:
            keys = sorted(v)
        except TypeError:
            return _UNHASHABLE
        items = tuple((k, _freeze(v[k], depth + 1)) for k in keys)
        if any(i[1] is _UNHASHABLE for i in items):
            return _UNHASHABLE
        return ("dict", items)
    if callable(v):
        return _code_key(v, depth + 1)
    try:
        hash(v)
    except TypeError:
        return _UNHASHABLE
    return (type(v).__name__, v)


import types as _types

# callables without __code__ that are safe to key by identity: these kinds
# have no user-mutable behavioral state (a custom __call__ object does, so
# it must NOT be identity-keyed — its attributes can change between calls)
_IDENTITY_CALLABLES = (
    _types.BuiltinFunctionType,
    _types.MethodWrapperType,
)


def _identity_keyable(fn):
    if isinstance(fn, _IDENTITY_CALLABLES):
        return True
    mod = type(fn).__module__ or ""
    # numpy ufuncs and jax's custom_jvp/custom_vjp wrappers around
    # module-level functions (jax.nn.relu etc.)
    return mod.startswith("numpy") or mod.startswith("jax.")


def _code_key(fn, depth=0):
    code = getattr(fn, "__code__", None)
    if code is None:
        if not _identity_keyable(fn):
            return _UNHASHABLE
        try:
            hash(fn)
        except TypeError:
            return _UNHASHABLE
        return ("obj", fn)  # held strongly by the key, so identity is stable
    parts = []
    for c in fn.__closure__ or ():
        fr = _freeze(c.cell_contents, depth + 1)
        if fr is _UNHASHABLE:
            return _UNHASHABLE
        parts.append(fr)
    # default args are op config as much as closures are
    for d in (fn.__defaults__ or ()) + tuple(sorted((fn.__kwdefaults__ or {}).items())):
        fr = _freeze(d, depth + 1)
        if fr is _UNHASHABLE:
            return _UNHASHABLE
        parts.append(fr)
    return (code, tuple(parts))


_last_salt_mesh = None
# memoized module refs: _dispatch_salt runs on EVERY eager op, and the
# per-call `import` statements + sys.modules lookups it used to do were
# measurable at lenet_eager scale (~30k ops/s); modules never unload, so
# one resolution is enough (flash_attention may not be imported yet —
# retry the lookup only while unresolved)
_mesh_mod = None
_fa_mod = None


def _dispatch_salt():
    """Global state an op's lowering may read without it being an input.
    A mesh change clears the whole cache — entries keyed on a dead mesh
    could never hit again and would strand compiled executables (same
    staleness class as the GPT pipe-cache advisor finding)."""
    global _last_salt_mesh, _mesh_mod, _fa_mod
    if _mesh_mod is None:
        from ..distributed import mesh as _mesh_mod_

        _mesh_mod = _mesh_mod_
    mesh = _mesh_mod.get_mesh()
    if mesh is not _last_salt_mesh:
        _EAGER_STATS["invalidations"] += len(_EAGER_CACHE)
        _EAGER_CACHE.clear()
        _last_salt_mesh = mesh
    amp = _core.active_amp()
    amp_key = (amp.enabled, amp.level, amp.dtype) if amp is not None else None
    # behavior-controlling module globals op bodies read at trace time —
    # without them a flag flip after a same-shape call would silently return
    # the stale cached executable (e.g. a test forcing the Pallas interpret
    # path getting the previously-compiled XLA path)
    if _fa_mod is None:
        import sys

        _fa_mod = sys.modules.get("paddle_tpu.ops.flash_attention")
    fa_key = getattr(_fa_mod, "_FORCE_INTERPRET", None)
    return (mesh, amp_key, _core.flag("FLAGS_check_nan_inf"),
            _core.flag("FLAGS_serve_kv_quant"), fa_key)


def _cache_get(key, builder):
    entry = _EAGER_CACHE.get(key)
    if entry is None:
        _EAGER_STATS["misses"] += 1
        try:
            from ..analysis import sanitizer as _san

            # a miss in a steady-state region is a GRAFT021 finding: the
            # eager path is building an executable mid-hot-loop
            _san.note_eager_miss(str(key[0]) if isinstance(key, tuple) else str(key))
        except Exception:
            pass
        entry = builder()
        _EAGER_CACHE[key] = entry
        cap = _eager_cache_cap()
        while len(_EAGER_CACHE) > cap:
            _EAGER_CACHE.popitem(last=False)
            _EAGER_STATS["evictions"] += 1
    else:
        _EAGER_STATS["hits"] += 1
        _EAGER_CACHE.move_to_end(key)
    return entry


def _bwd_apply():
    global _BWD_APPLY
    if _BWD_APPLY is None:
        _BWD_APPLY = jax.jit(lambda vf, cts: vf(cts))
    return _BWD_APPLY


def wrap(arr, stop_gradient=True):
    from ..tensor import Tensor

    t = Tensor.__new__(Tensor)
    return t._init_from_array(arr, stop_gradient=stop_gradient)


def coerce(x, dtype=None):
    """Promote python scalars / numpy / jax arrays to Tensor."""
    from ..tensor import Tensor

    if isinstance(x, Tensor):
        return x
    if isinstance(x, (bool, int, float, complex)):
        if dtype is None:
            if isinstance(x, bool):
                dtype = "bool"
            elif isinstance(x, int):
                dtype = "int64"
            elif isinstance(x, float):
                dtype = _core.get_default_dtype()
            else:
                dtype = "complex64"
        return wrap(jnp.asarray(x, _core.to_jax_dtype(dtype)))
    if isinstance(x, jax.Array) or isinstance(x, jax.core.Tracer):
        return wrap(x)
    return Tensor(x, dtype=dtype)


class _CaptureRecorder:
    """Records every Tensor flowing through apply() while installed —
    static.nn.cond/while_loop run a discovery pass under one to learn which
    outer tensors a branch/body closure captures, so those can become
    explicit lax.cond/scan operands (and receive gradients)."""

    def __init__(self):
        self.inputs = []
        self.created = set()
        # id(tensor) -> payload when FIRST seen: the discovery pass diffs
        # these afterwards to enforce the purity contract (a branch that
        # writes to pre-existing state would otherwise leave abstract
        # values in live tensors)
        self.snapshots = {}

    def captured(self):
        out, seen = [], set()
        for t in self.inputs:
            if id(t) in self.created or id(t) in seen:
                continue
            seen.add(id(t))
            out.append(t)
        return out


_capture_recorder = None


def apply(fn, inputs, name=None, multi=False, outputs_stop_gradient=None):
    """Execute `fn(*arrays)` over the inputs' payloads; record autograd.

    fn        : jax-traceable callable, one positional arg per input tensor.
    inputs    : list[Tensor]
    multi     : fn returns a tuple of arrays (else a single array)
    outputs_stop_gradient : optional list[bool] forcing per-output flags
    """
    from .. import autograd  # noqa: F401  (ensures engine import)
    from ..autograd.engine import GradNode

    arrays = [t._data for t in inputs]
    record = _core.grad_enabled() and any(
        (not t.stop_gradient) and _is_inexact(a) for t, a in zip(inputs, arrays)
    )

    # eager fast path eligibility: concrete arrays, no active trace, and a
    # closure we can key by value
    eager = _core.active_trace() is None and not any(
        isinstance(a, jax.core.Tracer) for a in arrays
    )
    ckey = _code_key(fn) if eager else _UNHASHABLE
    if ckey is not _UNHASHABLE:
        avals = tuple((tuple(a.shape), jnp.dtype(a.dtype)) for a in arrays)
        ckey = (ckey, avals, multi, _dispatch_salt())

    if _capture_recorder is not None:
        _capture_recorder.inputs.extend(inputs)
        for t in inputs:
            _capture_recorder.snapshots.setdefault(id(t), t._data)

    if not record:
        if ckey is not _UNHASHABLE:
            jfn = _cache_get(("fwd", ckey), lambda: jax.jit(lambda *ar: fn(*ar)))
            out = jfn(*arrays)
        else:
            out = fn(*arrays)
        outs = out if multi else (out,)
        tensors = tuple(wrap(o) for o in outs)
        if outputs_stop_gradient is not None:
            for t, sg in zip(tensors, outputs_stop_gradient):
                t.stop_gradient = sg
        if _capture_recorder is not None:
            _capture_recorder.created.update(id(t) for t in tensors)
        if _core.flag("FLAGS_check_nan_inf"):
            _check_nan_inf(name or "op", tensors)
        return tensors if multi else tensors[0]

    diff_idx = [
        i
        for i, (t, a) in enumerate(zip(inputs, arrays))
        if (not t.stop_gradient) and _is_inexact(a)
    ]

    def f(*diff):
        buf = list(arrays)
        for i, a in zip(diff_idx, diff):
            buf[i] = a
        r = fn(*buf)
        return r if multi else (r,)

    primals = [arrays[i] for i in diff_idx]
    if ckey is not _UNHASHABLE:
        vkey = ("vjp", ckey, tuple(diff_idx))
        nd_idx = [i for i in range(len(arrays)) if i not in diff_idx]

        def build():
            # fn from THIS call is baked in; the key guarantees any later
            # hit has byte-identical code and closure values
            captured_fn = fn

            def fwd(diff, nondiff):
                def g(*d):
                    buf = [None] * (len(diff_idx) + len(nd_idx))
                    for i, a in zip(diff_idx, d):
                        buf[i] = a
                    for i, a in zip(nd_idx, nondiff):
                        buf[i] = a
                    r = captured_fn(*buf)
                    return r if multi else (r,)

                return jax.vjp(g, *diff)

            return jax.jit(fwd)

        fwd_jit = _cache_get(vkey, build)
        outs, raw_vjp = fwd_jit(tuple(primals), tuple(arrays[i] for i in nd_idx))
        bwd = _bwd_apply()
        vjp_fn = lambda cts, _vf=raw_vjp: bwd(_vf, cts)  # noqa: E731
    else:
        outs, vjp_fn = jax.vjp(f, *primals)

    tensors = tuple(
        wrap(o, stop_gradient=not _is_inexact(o)) for o in outs
    )
    if outputs_stop_gradient is not None:
        for t, sg in zip(tensors, outputs_stop_gradient):
            t.stop_gradient = sg

    node = GradNode(
        name or getattr(fn, "__name__", "op"),
        f,
        vjp_fn,
        [inputs[i] for i in diff_idx],
        tensors,
    )
    for j, t in enumerate(tensors):
        if not t.stop_gradient:
            t._grad_node = node
            t._out_index = j
    if _capture_recorder is not None:
        _capture_recorder.created.update(id(t) for t in tensors)
    if _core.flag("FLAGS_check_nan_inf"):
        _check_nan_inf(name or "op", tensors)
    return tensors if multi else tensors[0]


def _check_nan_inf(name, tensors):
    """FLAGS_check_nan_inf (reference: nan_inf_utils_detail).

    Eager: check immediately and raise with op attribution.  Traced
    (@to_static): record an all-finite reduction on the active trace; the
    compiled program returns the flags as extra outputs and the caller
    raises with the same attribution (SURVEY.md §5.2)."""
    tr = _core.active_trace()
    for t in tensors:
        a = t._raw
        if not _is_inexact(a):
            continue
        if isinstance(a, jax.core.Tracer):
            if tr is not None:
                tr.nan_checks.append((name, jnp.isfinite(a).all()))
            continue
        if not bool(jnp.isfinite(a).all()):
            raise FloatingPointError(f"NaN or Inf found in output of op '{name}'")


def inplace_rebind(target, result):
    """Make `target` alias `result` (data + autograd) — the in-place contract.

    The reference tracks in-place via version counters on shared buffers
    (paddle/fluid/eager/*); on XLA buffers are immutable, so `add_`-style ops
    compute functionally then rebind, keeping tape linkage intact.
    """
    target._data = result._data
    target._grad_node = result._grad_node
    target._out_index = result._out_index
    if not result.stop_gradient:
        target.stop_gradient = False
    return target


# ---------------------------------------------------------------------------
# AMP hook (O1): cast inputs for white-listed ops when auto_cast is active
# ---------------------------------------------------------------------------


def amp_cast_inputs(tensors, list_kind):
    """list_kind: 'white' (cast to amp dtype) or 'black' (cast to float32)."""
    amp = _core.active_amp()
    if amp is None or not amp.enabled or amp.level not in ("O1", "O2"):
        return tensors
    from . import cast as _cast

    out = []
    if list_kind == "white":
        target = amp.dtype
        for t in tensors:
            if t.dtype in ("float32", "float16", "bfloat16") and t.dtype != target:
                out.append(_cast(t, target))
            else:
                out.append(t)
    else:  # black
        for t in tensors:
            if t.dtype in ("float16", "bfloat16"):
                out.append(_cast(t, "float32"))
            else:
                out.append(t)
    return out
