"""Op dispatcher — the single Python→XLA boundary.

Replaces the reference's kernel dispatch stack (phi::KernelFactory selection +
generated ad_funcs, SURVEY.md §3.1): every framework op is a jax-traceable
function over arrays; `apply()` executes it (eagerly via jax's op cache, or
symbolically under @to_static tracing) and, when autograd is live, records one
GradNode whose VJP comes from `jax.vjp`.  AMP O1 casting hooks in here too
(reference: paddle/fluid/eager/amp_utils.h).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import core as _core


def _is_inexact(arr):
    return jnp.issubdtype(jnp.dtype(arr.dtype), jnp.inexact)


def wrap(arr, stop_gradient=True):
    from ..tensor import Tensor

    t = Tensor.__new__(Tensor)
    return t._init_from_array(arr, stop_gradient=stop_gradient)


def coerce(x, dtype=None):
    """Promote python scalars / numpy / jax arrays to Tensor."""
    from ..tensor import Tensor

    if isinstance(x, Tensor):
        return x
    if isinstance(x, (bool, int, float, complex)):
        if dtype is None:
            if isinstance(x, bool):
                dtype = "bool"
            elif isinstance(x, int):
                dtype = "int64"
            elif isinstance(x, float):
                dtype = _core.get_default_dtype()
            else:
                dtype = "complex64"
        return wrap(jnp.asarray(x, _core.to_jax_dtype(dtype)))
    if isinstance(x, jax.Array) or isinstance(x, jax.core.Tracer):
        return wrap(x)
    return Tensor(x, dtype=dtype)


def apply(fn, inputs, name=None, multi=False, outputs_stop_gradient=None):
    """Execute `fn(*arrays)` over the inputs' payloads; record autograd.

    fn        : jax-traceable callable, one positional arg per input tensor.
    inputs    : list[Tensor]
    multi     : fn returns a tuple of arrays (else a single array)
    outputs_stop_gradient : optional list[bool] forcing per-output flags
    """
    from .. import autograd  # noqa: F401  (ensures engine import)
    from ..autograd.engine import GradNode

    arrays = [t._data for t in inputs]
    record = _core.grad_enabled() and any(
        (not t.stop_gradient) and _is_inexact(a) for t, a in zip(inputs, arrays)
    )

    if not record:
        out = fn(*arrays)
        outs = out if multi else (out,)
        tensors = tuple(wrap(o) for o in outs)
        if outputs_stop_gradient is not None:
            for t, sg in zip(tensors, outputs_stop_gradient):
                t.stop_gradient = sg
        if _core.flag("FLAGS_check_nan_inf"):
            _check_nan_inf(name or "op", tensors)
        return tensors if multi else tensors[0]

    diff_idx = [
        i
        for i, (t, a) in enumerate(zip(inputs, arrays))
        if (not t.stop_gradient) and _is_inexact(a)
    ]

    def f(*diff):
        buf = list(arrays)
        for i, a in zip(diff_idx, diff):
            buf[i] = a
        r = fn(*buf)
        return r if multi else (r,)

    primals = [arrays[i] for i in diff_idx]
    outs, vjp_fn = jax.vjp(f, *primals)

    tensors = tuple(
        wrap(o, stop_gradient=not _is_inexact(o)) for o in outs
    )
    if outputs_stop_gradient is not None:
        for t, sg in zip(tensors, outputs_stop_gradient):
            t.stop_gradient = sg

    node = GradNode(
        name or getattr(fn, "__name__", "op"),
        f,
        vjp_fn,
        [inputs[i] for i in diff_idx],
        tensors,
    )
    for j, t in enumerate(tensors):
        if not t.stop_gradient:
            t._grad_node = node
            t._out_index = j
    if _core.flag("FLAGS_check_nan_inf"):
        _check_nan_inf(name or "op", tensors)
    return tensors if multi else tensors[0]


def _check_nan_inf(name, tensors):
    """FLAGS_check_nan_inf (reference: nan_inf_utils_detail).

    Eager: check immediately and raise with op attribution.  Traced
    (@to_static): record an all-finite reduction on the active trace; the
    compiled program returns the flags as extra outputs and the caller
    raises with the same attribution (SURVEY.md §5.2)."""
    tr = _core.active_trace()
    for t in tensors:
        a = t._raw
        if not _is_inexact(a):
            continue
        if isinstance(a, jax.core.Tracer):
            if tr is not None:
                tr.nan_checks.append((name, jnp.isfinite(a).all()))
            continue
        if not bool(jnp.isfinite(a).all()):
            raise FloatingPointError(f"NaN or Inf found in output of op '{name}'")


def inplace_rebind(target, result):
    """Make `target` alias `result` (data + autograd) — the in-place contract.

    The reference tracks in-place via version counters on shared buffers
    (paddle/fluid/eager/*); on XLA buffers are immutable, so `add_`-style ops
    compute functionally then rebind, keeping tape linkage intact.
    """
    target._data = result._data
    target._grad_node = result._grad_node
    target._out_index = result._out_index
    if not result.stop_gradient:
        target.stop_gradient = False
    return target


# ---------------------------------------------------------------------------
# AMP hook (O1): cast inputs for white-listed ops when auto_cast is active
# ---------------------------------------------------------------------------


def amp_cast_inputs(tensors, list_kind):
    """list_kind: 'white' (cast to amp dtype) or 'black' (cast to float32)."""
    amp = _core.active_amp()
    if amp is None or not amp.enabled or amp.level not in ("O1", "O2"):
        return tensors
    from . import cast as _cast

    out = []
    if list_kind == "white":
        target = amp.dtype
        for t in tensors:
            if t.dtype in ("float32", "float16", "bfloat16") and t.dtype != target:
                out.append(_cast(t, target))
            else:
                out.append(t)
    else:  # black
        for t in tensors:
            if t.dtype in ("float16", "bfloat16"):
                out.append(_cast(t, "float32"))
            else:
                out.append(t)
    return out
