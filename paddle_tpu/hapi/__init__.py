"""hapi — paddle.Model high-level fit/evaluate/predict + callbacks
(reference: python/paddle/hapi/model.py, python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import numpy as np

from .. import ops
from ..io import DataLoader
from ..tensor import Tensor


class Callback:
    """Reference: paddle.callbacks.Callback — hook points into fit()."""

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    """Reference: paddle.callbacks.ProgBarLogger (prints per log_freq)."""

    def __init__(self, log_freq=10, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and logs and step % self.log_freq == 0:
            items = " ".join(f"{k}: {v:.5f}" for k, v in logs.items() if isinstance(v, float))
            print(f"step {step}: {items}")


class ModelCheckpoint(Callback):
    """Reference: paddle.callbacks.ModelCheckpoint — saves per save_freq."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            import os

            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, str(epoch)))


class EarlyStopping(Callback):
    """Reference: paddle.callbacks.EarlyStopping on an eval metric."""

    def __init__(self, monitor="loss", mode="min", patience=0, min_delta=0, baseline=None, save_best_model=False):
        if save_best_model:
            raise NotImplementedError(
                "EarlyStopping(save_best_model=True) is not implemented; use "
                "callbacks.ModelCheckpoint alongside EarlyStopping"
            )
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.sign = -1 if mode == "min" else 1
        self.baseline = None if baseline is None else self.sign * baseline
        self.best = self.baseline
        self.wait = 0
        self.stop_training = False

    def on_eval_end(self, logs=None):
        if not logs or self.monitor not in logs:
            return
        cur = self.sign * logs[self.monitor]
        if self.best is None or cur > self.best + self.min_delta:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True


class _CallbackList:
    def __init__(self, callbacks, model):
        self.cbs = list(callbacks or [])
        for c in self.cbs:
            c.set_model(model)

    def call(self, hook, *args, **kwargs):
        for c in self.cbs:
            getattr(c, hook)(*args, **kwargs)

    @property
    def stop_training(self):
        return any(getattr(c, "stop_training", False) for c in self.cbs)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else ([metrics] if metrics else [])

    def _update_metrics(self, out, label):
        vals = {}
        for m in self._metrics:
            r = m.compute(out, label)
            # the base Metric.compute passes (pred, label) through as a
            # tuple for update(pred, label)-style metrics (Precision etc.)
            if isinstance(r, (tuple, list)):
                m.update(*r)
            else:
                m.update(r)
            acc = m.accumulate()
            names = m.name()
            if isinstance(acc, (tuple, list)):
                if not isinstance(names, (tuple, list)):
                    names = [f"{names}_top{k}" for k in getattr(m, "topk", range(1, len(acc) + 1))]
                for n, v in zip(names, acc):
                    vals[n] = float(v)
            else:
                vals[names if not isinstance(names, (tuple, list)) else names[0]] = float(acc)
        return vals

    def train_batch(self, inputs, labels=None):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*inputs)
        label = labels if not isinstance(labels, (list, tuple)) else labels[0]
        loss = self._loss(out, label)
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        self._last_metrics = self._update_metrics(out, label)
        return [float(loss.numpy())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*inputs)
        label = labels if not isinstance(labels, (list, tuple)) else labels[0]
        loss = self._loss(out, label)
        self._last_metrics = self._update_metrics(out, label)
        return [float(loss.numpy())]

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        return self.network(*inputs)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1, eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2, drop_last=False, shuffle=True, num_workers=0, callbacks=None, max_bad_steps=10):
        """Train the model (reference: paddle.Model.fit), under a
        fault.Supervisor: `max_bad_steps` consecutive non-finite losses
        abort with a diagnostic (NonFiniteLossError) instead of burning
        compute on a diverged job, and SIGTERM/preemption checkpoints
        best-effort (to `save_dir/preempt` when save_dir is set) and exits
        with the restart-requested code the launch controller honors.
        Pass max_bad_steps=0 to disable the watchdog."""
        from ..fault import Supervisor
        from ..fault import watchdog as _wd

        loader = train_data if isinstance(train_data, DataLoader) else DataLoader(
            train_data, batch_size=batch_size, shuffle=shuffle, drop_last=drop_last, num_workers=num_workers
        )
        cbs = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
            cbs.append(ProgBarLogger(log_freq, verbose))
        if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbs):
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        cblist = _CallbackList(cbs, self)

        save_fn = None
        if save_dir:
            def save_fn():
                import os

                os.makedirs(save_dir, exist_ok=True)
                self.save(os.path.join(save_dir, "preempt"))

        cblist.call("on_train_begin")
        history = []
        with Supervisor(save_fn=save_fn, max_bad_steps=max_bad_steps) as sup:
            for epoch in range(epochs):
                cblist.call("on_epoch_begin", epoch)
                for m in self._metrics:
                    m.reset()
                losses = []
                for step, batch in enumerate(loader):
                    cblist.call("on_train_batch_begin", step)
                    x, y = batch[0], batch[1]
                    with sup.guard(), _wd.arm("fit.train_batch", context=f"step {step}"):
                        loss = self.train_batch(x, y)[0]
                    losses.append(loss)
                    logs = {"loss": loss, **getattr(self, "_last_metrics", {})}
                    cblist.call("on_train_batch_end", step, logs)
                    sup.after_step(loss)
                epoch_logs = {"loss": float(np.mean(losses)), **getattr(self, "_last_metrics", {})}
                history.append(epoch_logs["loss"])
                cblist.call("on_epoch_end", epoch, epoch_logs)
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    cblist.call("on_eval_begin")
                    result = self.evaluate(eval_data, batch_size=batch_size, verbose=verbose)
                    cblist.call("on_eval_end", result)
                if cblist.stop_training:
                    break
        cblist.call("on_train_end")
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0, callbacks=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(eval_data, batch_size=batch_size)
        cblist = _CallbackList(callbacks, self)
        cblist.call("on_eval_begin")
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            x, y = batch[0], batch[1]
            losses.append(self.eval_batch(x, y)[0])
        result = {"loss": float(np.mean(losses)), **getattr(self, "_last_metrics", {})}
        cblist.call("on_eval_end", result)
        if verbose:
            print(f"eval: {result}")
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(test_data, batch_size=batch_size)
        outs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(self.predict_batch(x))
        return outs

    def parameters(self):
        return self.network.parameters()

    def save(self, path, training=True):
        from ..framework.io import save

        save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load

        self.network.set_state_dict(load(path + ".pdparams"))

    def summary(self, input_size=None, dtype=None):
        total = sum(p.size for p in self.network.parameters())
        print(f"Total params: {total}")
        return {"total_params": total}
