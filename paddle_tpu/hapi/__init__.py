"""hapi — paddle.Model high-level fit/evaluate/predict + callbacks
(reference: python/paddle/hapi/model.py, python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import contextlib

from .. import ops
from ..io import DataLoader
from ..tensor import Tensor


def _batch_signature(*tensors):
    """(shape, dtype) tuple per input, or None when an input has no shape —
    the fit loop compares consecutive signatures to decide when the step
    dispatch has entered steady state (same signature => no retrace is
    legitimate)."""
    sig = []
    for t in tensors:
        shape = getattr(t, "shape", None)
        if shape is None:
            return None
        sig.append((tuple(shape), str(getattr(t, "dtype", ""))))
    return tuple(sig)


def _materialize_losses(raws):
    """ONE host sync for a window of device-resident scalar losses: stack
    on device, fetch together.  Routed through Tensor.numpy so sync-audit
    tooling (tests monkeypatch-count blocking materializations) sees it."""
    import jax.numpy as jnp

    return Tensor(
        jnp.stack([jnp.reshape(r, ()).astype(jnp.float32) for r in raws])
    ).numpy()


class Callback:
    """Reference: paddle.callbacks.Callback — hook points into fit()."""

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    """Reference: paddle.callbacks.ProgBarLogger (prints per log_freq)."""

    def __init__(self, log_freq=10, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and logs and step % self.log_freq == 0:
            items = " ".join(f"{k}: {v:.5f}" for k, v in logs.items() if isinstance(v, float))
            print(f"step {step}: {items}")


class ModelCheckpoint(Callback):
    """Reference: paddle.callbacks.ModelCheckpoint — saves per save_freq."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            import os

            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, str(epoch)))


class EarlyStopping(Callback):
    """Reference: paddle.callbacks.EarlyStopping on an eval metric."""

    def __init__(self, monitor="loss", mode="min", patience=0, min_delta=0, baseline=None, save_best_model=False):
        if save_best_model:
            raise NotImplementedError(
                "EarlyStopping(save_best_model=True) is not implemented; use "
                "callbacks.ModelCheckpoint alongside EarlyStopping"
            )
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.sign = -1 if mode == "min" else 1
        self.baseline = None if baseline is None else self.sign * baseline
        self.best = self.baseline
        self.wait = 0
        self.stop_training = False

    def on_eval_end(self, logs=None):
        if not logs or self.monitor not in logs:
            return
        cur = self.sign * logs[self.monitor]
        if self.best is None or cur > self.best + self.min_delta:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True


class _CallbackList:
    def __init__(self, callbacks, model):
        self.cbs = list(callbacks or [])
        for c in self.cbs:
            c.set_model(model)

    def call(self, hook, *args, **kwargs):
        for c in self.cbs:
            getattr(c, hook)(*args, **kwargs)

    @property
    def stop_training(self):
        return any(getattr(c, "stop_training", False) for c in self.cbs)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else ([metrics] if metrics else [])

    def _metrics_update(self, out, label):
        """Feed each metric one batch WITHOUT reading accumulators: the
        device path (update_on_device) keeps running sums as jax arrays —
        zero host syncs — and the host compute/update path is the fallback
        for metrics without one.  Accumulator reads (the float() storm the
        seed paid per step) happen only in _collect_metrics, at
        log_freq/epoch boundaries that actually consume them."""
        for m in self._metrics:
            if m.update_on_device(out, label):
                continue
            r = m.compute(out, label)
            # the base Metric.compute passes (pred, label) through as a
            # tuple for update(pred, label)-style metrics (Precision etc.)
            if isinstance(r, (tuple, list)):
                m.update(*r)
            else:
                m.update(r)

    def _collect_metrics(self):
        """Reduce every metric to Python floats (the only sync point of the
        metrics pipeline)."""
        vals = {}
        for m in self._metrics:
            acc = m.accumulate()
            names = m.name()
            if isinstance(acc, (tuple, list)):
                if not isinstance(names, (tuple, list)):
                    names = [f"{names}_top{k}" for k in getattr(m, "topk", range(1, len(acc) + 1))]
                for n, v in zip(names, acc):
                    vals[n] = float(v)
            else:
                vals[names if not isinstance(names, (tuple, list)) else names[0]] = float(acc)
        return vals

    def _update_metrics(self, out, label):
        # compat shim for the seed's update+read-per-step shape
        self._metrics_update(out, label)
        return self._collect_metrics()

    def train_batch(self, inputs, labels=None):
        """One optimizer step.  The returned loss is DEVICE-RESIDENT — the
        host dispatches the step and moves on; materialize with
        float()/.numpy() only where the value is consumed (fit does so at
        log_freq boundaries).  Metrics accumulate on device too."""
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*inputs)
        label = labels if not isinstance(labels, (list, tuple)) else labels[0]
        loss = self._loss(out, label)
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        self._metrics_update(out, label)
        return [loss]

    def eval_batch(self, inputs, labels=None):
        """Forward + loss; device-resident return, same contract as
        train_batch."""
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*inputs)
        label = labels if not isinstance(labels, (list, tuple)) else labels[0]
        loss = self._loss(out, label)
        self._metrics_update(out, label)
        return [loss]

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        return self.network(*inputs)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1, eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2, drop_last=False, shuffle=True, num_workers=0, callbacks=None, max_bad_steps=10):
        """Train the model (reference: paddle.Model.fit), under a
        fault.Supervisor: `max_bad_steps` consecutive non-finite losses
        abort with a diagnostic (NonFiniteLossError) instead of burning
        compute on a diverged job, and SIGTERM/preemption checkpoints
        best-effort (to `save_dir/preempt` when save_dir is set) and exits
        with the restart-requested code the launch controller honors.
        Pass max_bad_steps=0 to disable the watchdog.

        ASYNC STEP PIPELINE: the loop never blocks on a step's loss value.
        Device-resident losses accumulate in a window; the host materializes
        them (ONE stacked fetch) only at log_freq boundaries and epoch ends —
        the points whose callbacks actually consume floats.  The supervisor's
        NaN watchdog drains the same window at the same boundaries, so
        divergence detection latency is bounded by log_freq without a
        per-step sync.  FLAGS_max_inflight_steps bounds how far the host
        runs ahead of the device (backpressure via block_until_ready — a
        completion wait, not a value transfer); set it to 1 for the strict
        per-step sync loop (identical numerics, the seed behavior)."""
        import collections
        import time

        from ..fault import Supervisor
        from ..analysis import sanitizer as _san
        from ..fault import watchdog as _wd
        from ..framework import core as _core
        from ..obs import trace as _obs
        from .. import profiler as _prof

        loader = train_data if isinstance(train_data, DataLoader) else DataLoader(
            train_data, batch_size=batch_size, shuffle=shuffle, drop_last=drop_last, num_workers=num_workers
        )
        cbs = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
            cbs.append(ProgBarLogger(log_freq, verbose))
        if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbs):
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        cblist = _CallbackList(cbs, self)

        save_fn = None
        if save_dir:
            def save_fn():
                import os

                os.makedirs(save_dir, exist_ok=True)
                self.save(os.path.join(save_dir, "preempt"))

        inflight = max(1, int(_core.flag("FLAGS_max_inflight_steps")))
        sync_mode = inflight <= 1
        cblist.call("on_train_begin")
        # one trace per fit() run: per-step dispatch spans parent on the
        # enclosing materialize window, so the async pipeline's shape
        # (many dispatches, one sync) is visible in the trace viewer
        fit_tid = _obs.new_trace_id()
        history = []
        with Supervisor(save_fn=save_fn, max_bad_steps=max_bad_steps) as sup:
            for epoch in range(epochs):
                cblist.call("on_epoch_begin", epoch)
                for m in self._metrics:
                    m.reset()
                epoch_sum, epoch_n = 0.0, 0
                window = []  # device losses since the last sync point
                ring = collections.deque()  # bounded in-flight steps
                # pre-minted window span id: fit.step spans parent on it
                win = {"sid": _obs.new_span_id(), "t0": time.perf_counter(),
                       "steps": 0}

                def _materialize():
                    """One host sync for the whole window: the stacked
                    losses come back together, and the supervisor ring
                    drains with the SAME values (no second round-trip)."""
                    nonlocal epoch_sum, epoch_n, window, win
                    n_win = len(window)
                    vals = _materialize_losses(window)
                    window = []
                    ring.clear()  # everything up to here has retired
                    sup.drain(values=vals)
                    for v in vals:  # per-value float64 adds: the epoch mean
                        epoch_sum += float(v)  # is window-size invariant
                    epoch_n += len(vals)
                    t_now = time.perf_counter()
                    _obs.record("fit.window", fit_tid, t0=win["t0"], t1=t_now,
                                span_id=win["sid"], epoch=epoch,
                                steps=win["steps"], losses=n_win)
                    win = {"sid": _obs.new_span_id(), "t0": t_now, "steps": 0}
                    return vals

                last_end = time.perf_counter()
                prev_sig = None
                for step, batch in enumerate(loader):
                    cblist.call("on_train_batch_begin", step)
                    x, y = batch[0], batch[1]
                    # once the batch signature repeats, the step dispatch is
                    # steady-state: a fresh trace (a shape/dtype leak) or a
                    # host sync inside train_batch is a sanitizer finding.
                    # A changed signature (first step, ragged last batch) is
                    # a legitimate retrace and stays outside the region.
                    sig = _batch_signature(x, y)
                    ss = (
                        _san.steady_state("fit.inflight_ring")
                        if sig is not None and sig == prev_sig and _san.enabled()
                        else contextlib.nullcontext()
                    )
                    prev_sig = sig
                    t0 = time.perf_counter()
                    with sup.guard(), _wd.arm("fit.train_batch", context=f"step {step}"):
                        with ss:
                            loss_t = self.train_batch(x, y)[0]
                    t1 = time.perf_counter()
                    _obs.record("fit.step", fit_tid, t0=t0, t1=t1,
                                parent_id=win["sid"], step=step, epoch=epoch)
                    win["steps"] += 1
                    window.append(getattr(loss_t, "_raw", loss_t))
                    sup.after_step(loss_t)  # deferred: heartbeat + preemption
                    # poll now, finiteness at the next drain
                    host_block = 0.0
                    if not sync_mode:
                        ring.append(window[-1])
                        if len(ring) > inflight:
                            tb = time.perf_counter()
                            old = ring.popleft()
                            if hasattr(old, "block_until_ready"):
                                old.block_until_ready()
                            host_block += time.perf_counter() - tb
                    if sync_mode or step % log_freq == 0:
                        tb = time.perf_counter()
                        vals = _materialize()  # may raise NonFiniteLossError
                        host_block += time.perf_counter() - tb
                        logs = {"loss": float(vals[-1]), **self._collect_metrics()}
                    else:
                        # between boundaries callbacks get the live device
                        # tensor — consuming it (float()) is the caller
                        # opting into a sync
                        logs = {"loss": loss_t}
                    cblist.call("on_train_batch_end", step, logs)
                    now = time.perf_counter()
                    _prof.record_step(
                        dispatch_s=t1 - t0,
                        host_blocked_s=host_block,
                        inflight=len(ring),
                        wall_s=now - last_end,
                    )
                    last_end = now
                if window:
                    _materialize()  # epoch-end sync: mean loss + NaN drain
                epoch_logs = {
                    "loss": epoch_sum / max(epoch_n, 1),
                    **self._collect_metrics(),
                }
                history.append(epoch_logs["loss"])
                cblist.call("on_epoch_end", epoch, epoch_logs)
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    cblist.call("on_eval_begin")
                    result = self.evaluate(eval_data, batch_size=batch_size, verbose=verbose)
                    cblist.call("on_eval_end", result)
                if cblist.stop_training:
                    break
        cblist.call("on_train_end")
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0, callbacks=None):
        """Evaluation loop — fully async: per-batch losses stay on device
        and are materialized once at eval end (metrics likewise)."""
        import collections

        from ..framework import core as _core

        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(eval_data, batch_size=batch_size)
        cblist = _CallbackList(callbacks, self)
        cblist.call("on_eval_begin")
        for m in self._metrics:
            m.reset()
        inflight = max(1, int(_core.flag("FLAGS_max_inflight_steps")))
        raws = []
        ring = collections.deque()
        for batch in loader:
            x, y = batch[0], batch[1]
            loss_t = self.eval_batch(x, y)[0]
            raws.append(getattr(loss_t, "_raw", loss_t))
            ring.append(raws[-1])
            if len(ring) > inflight:
                old = ring.popleft()
                if hasattr(old, "block_until_ready"):
                    old.block_until_ready()
        mean = float(_materialize_losses(raws).mean()) if raws else float("nan")
        result = {"loss": mean, **self._collect_metrics()}
        cblist.call("on_eval_end", result)
        if verbose:
            print(f"eval: {result}")
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(test_data, batch_size=batch_size)
        outs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(self.predict_batch(x))
        return outs

    def parameters(self):
        return self.network.parameters()

    def save(self, path, training=True):
        from ..framework.io import save

        save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        """Load weights from `path + ".pdparams"`, and — when an optimizer
        is prepared — its accumulators/master weights from `path + ".pdopt"`
        if that file exists.  `reset_optimizer=True` instead discards all
        optimizer statistics (fresh moments, step count 0), the reference
        paddle.Model.load contract.  `skip_mismatch` maps to the
        optimizer's non-strict restore (unmatched entries warn, not
        raise)."""
        import os

        from ..framework.io import load

        self.network.set_state_dict(load(path + ".pdparams"))
        opt = self._optimizer
        if opt is None:
            return
        if reset_optimizer:
            for attr in ("_accumulators", "_master_weights"):
                d = getattr(opt, attr, None)
                if isinstance(d, dict):
                    d.clear()
            if hasattr(opt, "_step_count"):
                opt._step_count = 0
            return
        opt_path = path + ".pdopt"
        if os.path.exists(opt_path):
            opt.set_state_dict(load(opt_path), strict=not skip_mismatch)

    def summary(self, input_size=None, dtype=None):
        total = sum(p.size for p in self.network.parameters())
        print(f"Total params: {total}")
        return {"total_params": total}
