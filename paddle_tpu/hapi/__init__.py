# placeholder during bring-up
class Model:
    pass
