"""hapi — paddle.Model high-level fit/evaluate/predict
(reference: python/paddle/hapi/model.py)."""

from __future__ import annotations

import numpy as np

from .. import ops
from ..io import DataLoader
from ..tensor import Tensor


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else ([metrics] if metrics else [])

    def train_batch(self, inputs, labels=None):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*inputs)
        loss = self._loss(out, labels if not isinstance(labels, (list, tuple)) else labels[0])
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        return [float(loss.numpy())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*inputs)
        loss = self._loss(out, labels if not isinstance(labels, (list, tuple)) else labels[0])
        return [float(loss.numpy())]

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        return self.network(*inputs)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1, eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2, drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        loader = train_data if isinstance(train_data, DataLoader) else DataLoader(
            train_data, batch_size=batch_size, shuffle=shuffle, drop_last=drop_last, num_workers=num_workers
        )
        history = []
        for epoch in range(epochs):
            losses = []
            for step, batch in enumerate(loader):
                x, y = batch[0], batch[1]
                loss = self.train_batch(x, y)[0]
                losses.append(loss)
                if verbose and step % log_freq == 0:
                    print(f"epoch {epoch} step {step}: loss {loss:.5f}")
            history.append(float(np.mean(losses)))
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size, verbose=verbose)
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0, callbacks=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(eval_data, batch_size=batch_size)
        losses = []
        for batch in loader:
            x, y = batch[0], batch[1]
            losses.append(self.eval_batch(x, y)[0])
        result = {"loss": float(np.mean(losses))}
        if verbose:
            print(f"eval: {result}")
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(test_data, batch_size=batch_size)
        outs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(self.predict_batch(x))
        return outs

    def parameters(self):
        return self.network.parameters()

    def save(self, path, training=True):
        from ..framework.io import save

        save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load

        self.network.set_state_dict(load(path + ".pdparams"))

    def summary(self, input_size=None, dtype=None):
        total = sum(p.size for p in self.network.parameters())
        print(f"Total params: {total}")
        return {"total_params": total}
