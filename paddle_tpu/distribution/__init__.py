"""paddle.distribution — probability distributions (reference:
python/paddle/distribution/ — Distribution base, Normal/Uniform/
Categorical/Bernoulli/Beta/Dirichlet/..., kl_divergence + register_kl).

TPU-native: every density/statistic is a jnp expression routed through the
dispatch layer (differentiable, jit-traceable); sampling threads the global
Generator key (framework/random.py) so it is reproducible under
paddle.seed and becomes threaded state inside @to_static steps.
"""

from __future__ import annotations

import math

import numpy as np

from ..framework.random import default_generator
from ..ops.dispatch import apply, coerce, wrap

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
    "Beta", "Dirichlet", "Exponential", "Gamma", "Laplace", "LogNormal",
    "Gumbel", "Multinomial", "Independent", "kl_divergence", "register_kl",
]


def _shape_tuple(shape):
    if shape is None:
        return ()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


class Distribution:
    """Base class (reference: paddle.distribution.Distribution)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = _shape_tuple(batch_shape)
        self._event_shape = _shape_tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    # -- interface ----------------------------------------------------------
    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        import jax.numpy as jnp

        return apply(lambda lp: jnp.exp(lp), [coerce(self.log_prob(value))], name="prob")

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _key(self):
        return default_generator.next_key()


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = coerce(loc, dtype="float32")
        self.scale = coerce(scale, dtype="float32")
        super().__init__(tuple(self.loc.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale * self.scale

    @property
    def stddev(self):
        return self.scale

    def sample(self, shape=()):
        import jax

        shape = _shape_tuple(shape)
        key = self._key()

        def f(loc, sc):
            eps = jax.random.normal(key, shape + loc.shape, loc.dtype)
            return loc + sc * eps

        out = apply(f, [self.loc, self.scale], name="normal_sample")
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        import jax

        shape = _shape_tuple(shape)
        key = self._key()
        return apply(
            lambda loc, sc: loc + sc * jax.random.normal(key, shape + loc.shape, loc.dtype),
            [self.loc, self.scale],
            name="normal_rsample",
        )

    def log_prob(self, value):
        import jax.numpy as jnp

        return apply(
            lambda v, loc, sc: -((v - loc) ** 2) / (2 * sc**2)
            - jnp.log(sc)
            - 0.5 * math.log(2 * math.pi),
            [coerce(value), self.loc, self.scale],
            name="normal_log_prob",
        )

    def entropy(self):
        import jax.numpy as jnp

        return apply(
            lambda sc: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(sc),
            [self.scale],
            name="normal_entropy",
        )

    def cdf(self, value):
        import jax

        return apply(
            lambda v, loc, sc: jax.scipy.stats.norm.cdf(v, loc, sc),
            [coerce(value), self.loc, self.scale],
            name="normal_cdf",
        )


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = coerce(loc, dtype="float32")
        self.scale = coerce(scale, dtype="float32")
        self._base = Normal(loc, scale)
        super().__init__(tuple(self.loc.shape))

    @property
    def mean(self):
        import jax.numpy as jnp

        return apply(
            lambda m, s: jnp.exp(m + s * s / 2), [self.loc, self.scale], name="lognormal_mean"
        )

    @property
    def variance(self):
        import jax.numpy as jnp

        return apply(
            lambda m, s: (jnp.exp(s * s) - 1) * jnp.exp(2 * m + s * s),
            [self.loc, self.scale],
            name="lognormal_var",
        )

    def sample(self, shape=()):
        import jax.numpy as jnp

        out = apply(lambda x: jnp.exp(x), [self._base.sample(shape)], name="exp")
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        import jax.numpy as jnp

        return apply(lambda x: jnp.exp(x), [self._base.rsample(shape)], name="exp")

    def log_prob(self, value):
        import jax.numpy as jnp

        return apply(
            lambda v, m, s: -((jnp.log(v) - m) ** 2) / (2 * s**2)
            - jnp.log(v * s)
            - 0.5 * math.log(2 * math.pi),
            [coerce(value), self.loc, self.scale],
            name="lognormal_log_prob",
        )

    def entropy(self):
        import jax.numpy as jnp

        return apply(
            lambda m, s: m + 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
            [self.loc, self.scale],
            name="lognormal_entropy",
        )


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = coerce(low, dtype="float32")
        self.high = coerce(high, dtype="float32")
        super().__init__(tuple(self.low.shape))

    @property
    def mean(self):
        return (self.low + self.high) / 2.0

    @property
    def variance(self):
        d = self.high - self.low
        return d * d / 12.0

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        import jax

        shape = _shape_tuple(shape)
        key = self._key()
        return apply(
            lambda lo, hi: lo + (hi - lo) * jax.random.uniform(key, shape + lo.shape, lo.dtype),
            [self.low, self.high],
            name="uniform_sample",
        )

    def log_prob(self, value):
        import jax.numpy as jnp

        return apply(
            lambda v, lo, hi: jnp.where(
                (v >= lo) & (v < hi), -jnp.log(hi - lo), -jnp.inf
            ),
            [coerce(value), self.low, self.high],
            name="uniform_log_prob",
        )

    def entropy(self):
        import jax.numpy as jnp

        return apply(lambda lo, hi: jnp.log(hi - lo), [self.low, self.high], name="uniform_entropy")


class Categorical(Distribution):
    """logits: unnormalized log-probs [..., K] (reference accepts logits)."""

    def __init__(self, logits=None, probs=None, name=None):
        import jax.numpy as jnp

        if (logits is None) == (probs is None):
            raise ValueError("pass exactly one of logits / probs")
        if probs is not None:
            self.logits = apply(lambda p: jnp.log(p), [coerce(probs, dtype="float32")], name="log")
        else:
            self.logits = coerce(logits, dtype="float32")
        super().__init__(tuple(self.logits.shape[:-1]))

    @property
    def probs(self):
        import jax

        return apply(lambda lg: jax.nn.softmax(lg, -1), [self.logits], name="softmax")

    def sample(self, shape=()):
        import jax

        shape = _shape_tuple(shape)
        key = self._key()
        out = apply(
            lambda lg: jax.random.categorical(key, lg, shape=shape + lg.shape[:-1]),
            [self.logits],
            name="categorical_sample",
        )
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        import jax
        import jax.numpy as jnp

        return apply(
            lambda v, lg: jnp.take_along_axis(
                jax.nn.log_softmax(lg, -1), v[..., None].astype(jnp.int32), -1
            )[..., 0],
            [coerce(value), self.logits],
            name="categorical_log_prob",
        )

    def entropy(self):
        import jax
        import jax.numpy as jnp

        def f(lg):
            logp = jax.nn.log_softmax(lg, -1)
            return -(jnp.exp(logp) * logp).sum(-1)

        return apply(f, [self.logits], name="categorical_entropy")


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_t = coerce(probs, dtype="float32")
        super().__init__(tuple(self.probs_t.shape))

    @property
    def mean(self):
        return self.probs_t

    @property
    def variance(self):
        return self.probs_t * (1.0 - self.probs_t)

    def sample(self, shape=()):
        import jax

        shape = _shape_tuple(shape)
        key = self._key()
        out = apply(
            lambda p: jax.random.bernoulli(key, p, shape + p.shape).astype(p.dtype),
            [self.probs_t],
            name="bernoulli_sample",
        )
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        import jax.numpy as jnp

        eps = 1e-8

        return apply(
            lambda v, p: v * jnp.log(p + eps) + (1 - v) * jnp.log(1 - p + eps),
            [coerce(value), self.probs_t],
            name="bernoulli_log_prob",
        )

    def entropy(self):
        import jax.numpy as jnp

        eps = 1e-8
        return apply(
            lambda p: -(p * jnp.log(p + eps) + (1 - p) * jnp.log(1 - p + eps)),
            [self.probs_t],
            name="bernoulli_entropy",
        )


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = coerce(alpha, dtype="float32")
        self.beta = coerce(beta, dtype="float32")
        super().__init__(tuple(self.alpha.shape))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        import jax.numpy as jnp

        return apply(
            lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
            [self.alpha, self.beta],
            name="beta_var",
        )

    def sample(self, shape=()):
        import jax

        shape = _shape_tuple(shape)
        key = self._key()
        out = apply(
            lambda a, b: jax.random.beta(key, a, b, shape + a.shape),
            [self.alpha, self.beta],
            name="beta_sample",
        )
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        import jax.scipy.stats as jst

        return apply(
            lambda v, a, b: jst.beta.logpdf(v, a, b),
            [coerce(value), self.alpha, self.beta],
            name="beta_log_prob",
        )

    def entropy(self):
        import jax.scipy.special as jsp

        def f(a, b):
            return (
                jsp.betaln(a, b)
                - (a - 1) * jsp.digamma(a)
                - (b - 1) * jsp.digamma(b)
                + (a + b - 2) * jsp.digamma(a + b)
            )

        return apply(f, [self.alpha, self.beta], name="beta_entropy")


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = coerce(concentration, dtype="float32")
        shape = tuple(self.concentration.shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return self.concentration / self.concentration.sum(axis=-1, keepdim=True)

    def sample(self, shape=()):
        import jax

        shape = _shape_tuple(shape)
        key = self._key()
        out = apply(
            lambda c: jax.random.dirichlet(key, c, shape + c.shape[:-1]),
            [self.concentration],
            name="dirichlet_sample",
        )
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        import jax.numpy as jnp
        import jax.scipy.special as jsp

        def f(v, c):
            # batched form: sum (c-1) log v - log B(c)
            return ((c - 1) * jnp.log(v)).sum(-1) + jsp.gammaln(c.sum(-1)) - jsp.gammaln(c).sum(-1)

        return apply(f, [coerce(value), self.concentration], name="dirichlet_log_prob")

    def entropy(self):
        import jax.numpy as jnp
        import jax.scipy.special as jsp

        def f(c):
            c0 = c.sum(-1)
            k = c.shape[-1]
            logB = jsp.gammaln(c).sum(-1) - jsp.gammaln(c0)
            return (
                logB
                + (c0 - k) * jsp.digamma(c0)
                - ((c - 1) * jsp.digamma(c)).sum(-1)
            )

        return apply(f, [self.concentration], name="dirichlet_entropy")


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = coerce(rate, dtype="float32")
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return 1.0 / self.rate

    @property
    def variance(self):
        return 1.0 / (self.rate * self.rate)

    def sample(self, shape=()):
        import jax

        shape = _shape_tuple(shape)
        key = self._key()
        out = apply(
            lambda r: jax.random.exponential(key, shape + r.shape, r.dtype) / r,
            [self.rate],
            name="exponential_sample",
        )
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        import jax.numpy as jnp

        return apply(
            lambda v, r: jnp.where(v >= 0, jnp.log(r) - r * v, -jnp.inf),
            [coerce(value), self.rate],
            name="exponential_log_prob",
        )

    def entropy(self):
        import jax.numpy as jnp

        return apply(lambda r: 1.0 - jnp.log(r), [self.rate], name="exponential_entropy")


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = coerce(concentration, dtype="float32")
        self.rate = coerce(rate, dtype="float32")
        super().__init__(tuple(self.concentration.shape))

    @property
    def mean(self):
        return self.concentration / self.rate

    @property
    def variance(self):
        return self.concentration / (self.rate * self.rate)

    def sample(self, shape=()):
        import jax

        shape = _shape_tuple(shape)
        key = self._key()
        out = apply(
            lambda a, r: jax.random.gamma(key, a, shape + a.shape) / r,
            [self.concentration, self.rate],
            name="gamma_sample",
        )
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        import jax.scipy.stats as jst

        return apply(
            lambda v, a, r: jst.gamma.logpdf(v, a, scale=1.0 / r),
            [coerce(value), self.concentration, self.rate],
            name="gamma_log_prob",
        )

    def entropy(self):
        import jax.numpy as jnp
        import jax.scipy.special as jsp

        def f(a, r):
            return a - jnp.log(r) + jsp.gammaln(a) + (1 - a) * jsp.digamma(a)

        return apply(f, [self.concentration, self.rate], name="gamma_entropy")


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = coerce(loc, dtype="float32")
        self.scale = coerce(scale, dtype="float32")
        super().__init__(tuple(self.loc.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2.0 * self.scale * self.scale

    def sample(self, shape=()):
        import jax

        shape = _shape_tuple(shape)
        key = self._key()
        out = apply(
            lambda m, s: m + s * jax.random.laplace(key, shape + m.shape, m.dtype),
            [self.loc, self.scale],
            name="laplace_sample",
        )
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        import jax.numpy as jnp

        return apply(
            lambda v, m, s: -jnp.abs(v - m) / s - jnp.log(2 * s),
            [coerce(value), self.loc, self.scale],
            name="laplace_log_prob",
        )

    def entropy(self):
        import jax.numpy as jnp

        return apply(lambda s: 1.0 + jnp.log(2 * s), [self.scale], name="laplace_entropy")


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = coerce(loc, dtype="float32")
        self.scale = coerce(scale, dtype="float32")
        super().__init__(tuple(self.loc.shape))

    _EULER = 0.5772156649015329

    @property
    def mean(self):
        return self.loc + self.scale * self._EULER

    @property
    def variance(self):
        return (math.pi**2 / 6.0) * self.scale * self.scale

    def sample(self, shape=()):
        import jax

        shape = _shape_tuple(shape)
        key = self._key()
        out = apply(
            lambda m, s: m + s * jax.random.gumbel(key, shape + m.shape, m.dtype),
            [self.loc, self.scale],
            name="gumbel_sample",
        )
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        import jax.numpy as jnp

        def f(v, m, s):
            z = (v - m) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)

        return apply(f, [coerce(value), self.loc, self.scale], name="gumbel_log_prob")

    def entropy(self):
        import jax.numpy as jnp

        return apply(
            lambda s: jnp.log(s) + 1.0 + self._EULER, [self.scale], name="gumbel_entropy"
        )


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_t = coerce(probs, dtype="float32")
        shape = tuple(self.probs_t.shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return self.probs_t * float(self.total_count)

    def sample(self, shape=()):
        import jax
        import jax.numpy as jnp

        shape = _shape_tuple(shape)
        key = self._key()
        n = self.total_count

        def f(p):
            k = p.shape[-1]
            draws = jax.random.categorical(
                key, jnp.log(p), shape=(n,) + shape + p.shape[:-1]
            )
            return jax.nn.one_hot(draws, k, dtype=p.dtype).sum(0)

        out = apply(f, [self.probs_t], name="multinomial_sample")
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        import jax.numpy as jnp
        import jax.scipy.special as jsp

        def f(v, p):
            return (
                jsp.gammaln(v.sum(-1) + 1)
                - jsp.gammaln(v + 1).sum(-1)
                + (v * jnp.log(p)).sum(-1)
            )

        return apply(f, [coerce(value), self.probs_t], name="multinomial_log_prob")


class Independent(Distribution):
    """Reinterprets batch dims of a base distribution as event dims."""

    def __init__(self, base, reinterpreted_batch_rank=1):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bs = base.batch_shape
        if not 0 <= self.rank <= len(bs):
            raise ValueError(
                f"reinterpreted_batch_rank {self.rank} exceeds the base "
                f"distribution's batch rank {len(bs)} (batch_shape {bs})"
            )
        super().__init__(bs[: len(bs) - self.rank], bs[len(bs) - self.rank:])

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        for _ in range(self.rank):
            lp = lp.sum(axis=-1)
        return lp

    def entropy(self):
        e = self.base.entropy()
        for _ in range(self.rank):
            e = e.sum(axis=-1)
        return e


# ---------------------------------------------------------------------------
# KL divergence registry (reference: paddle.distribution.kl_divergence /
# register_kl dispatch by type pair)
# ---------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    for (tp, tq), fn in _KL_REGISTRY.items():
        if isinstance(p, tp) and isinstance(q, tq):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})"
    )


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    import jax.numpy as jnp

    return apply(
        lambda m1, s1, m2, s2: jnp.log(s2 / s1)
        + (s1**2 + (m1 - m2) ** 2) / (2 * s2**2)
        - 0.5,
        [p.loc, p.scale, q.loc, q.scale],
        name="kl_normal",
    )


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    import jax
    import jax.numpy as jnp

    def f(lp, lq):
        a = jax.nn.log_softmax(lp, -1)
        b = jax.nn.log_softmax(lq, -1)
        return (jnp.exp(a) * (a - b)).sum(-1)

    return apply(f, [p.logits, q.logits], name="kl_categorical")


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    import jax.numpy as jnp

    def f(lo1, hi1, lo2, hi2):
        ok = (lo2 <= lo1) & (hi1 <= hi2)
        return jnp.where(ok, jnp.log((hi2 - lo2) / (hi1 - lo1)), jnp.inf)

    return apply(f, [p.low, p.high, q.low, q.high], name="kl_uniform")


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    import jax.numpy as jnp

    eps = 1e-8

    def f(a, b):
        return a * (jnp.log(a + eps) - jnp.log(b + eps)) + (1 - a) * (
            jnp.log(1 - a + eps) - jnp.log(1 - b + eps)
        )

    return apply(f, [p.probs_t, q.probs_t], name="kl_bernoulli")


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    import jax.scipy.special as jsp

    def f(a1, b1, a2, b2):
        return (
            jsp.betaln(a2, b2)
            - jsp.betaln(a1, b1)
            + (a1 - a2) * jsp.digamma(a1)
            + (b1 - b2) * jsp.digamma(b1)
            + (a2 - a1 + b2 - b1) * jsp.digamma(a1 + b1)
        )

    return apply(f, [p.alpha, p.beta, q.alpha, q.beta], name="kl_beta")


@register_kl(Dirichlet, Dirichlet)
def _kl_dir_dir(p, q):
    import jax.scipy.special as jsp

    def f(c1, c2):
        s1 = c1.sum(-1)
        return (
            jsp.gammaln(s1)
            - jsp.gammaln(c2.sum(-1))
            - (jsp.gammaln(c1) - jsp.gammaln(c2)).sum(-1)
            + ((c1 - c2) * (jsp.digamma(c1) - jsp.digamma(s1)[..., None])).sum(-1)
        )

    return apply(f, [p.concentration, q.concentration], name="kl_dirichlet")


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    import jax.numpy as jnp

    return apply(
        lambda r1, r2: jnp.log(r1 / r2) + r2 / r1 - 1.0,
        [p.rate, q.rate],
        name="kl_exponential",
    )
