"""paddle.autograd public surface (reference: python/paddle/autograd/)."""

from __future__ import annotations

import contextlib
import functools

from ..framework import core as _core
from ..tensor import Tensor
from .engine import run_backward


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward."""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is not None and isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    run_backward(
        tensors,
        grad_tensors,
        inputs=None,
        accumulate_into_leaves=True,
        retain_graph=retain_graph,
    )


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
    name=None,
):
    """paddle.grad — compute grads of outputs w.r.t. inputs without touching .grad."""
    single_out = isinstance(outputs, Tensor)
    outputs = [outputs] if single_out else list(outputs)
    single_in = isinstance(inputs, Tensor)
    inputs = [inputs] if single_in else list(inputs)
    if grad_outputs is not None and isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = create_graph
    res = run_backward(
        outputs,
        grad_outputs,
        inputs=inputs,
        accumulate_into_leaves=False,
        create_graph=create_graph,
        retain_graph=retain_graph,
    )
    out = []
    for t in inputs:
        g = res.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(
                "One of the differentiated tensors appears to not have been used "
                "in the graph. Set allow_unused=True if this is desired."
            )
        out.append(g)
    return out


class no_grad:
    """Context manager AND decorator (paddle.no_grad)."""

    def __enter__(self):
        self._old = _core.set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        _core.set_grad_enabled(self._old)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._old = _core.set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        _core.set_grad_enabled(self._old)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with enable_grad():
                return fn(*args, **kwargs)

        return wrapper


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    old = _core.set_grad_enabled(mode)
    try:
        yield
    finally:
        _core.set_grad_enabled(old)


def is_grad_enabled():
    return _core.grad_enabled()


# ---------------------------------------------------------------------------
# PyLayer — custom autograd op (reference: python/paddle/autograd/py_layer.py)
# ---------------------------------------------------------------------------


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._materialize_grads = True
        self.__dict__["_attrs"] = {}

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_not_inplace(self, *a):
        pass

    def set_materialize_grads(self, v):
        self._materialize_grads = bool(v)


class PyLayerMeta(type):
    def __call__(cls, *a, **k):
        raise RuntimeError("PyLayer subclasses are used via .apply(...)")


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..autograd.engine import GradNode
        from ..ops.dispatch import wrap

        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        needs_grad = _core.grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs
        )
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        single = isinstance(out, Tensor)
        outputs = [out] if single else list(out)
        if not needs_grad:
            return out

        out_tensors = []
        for o in outputs:
            t = o.detach()
            t.stop_gradient = False
            out_tensors.append(t)

        def vjp_fn(cotangents):
            cts = [wrap(c) for c in cotangents]
            with no_grad():
                gin = cls.backward(ctx, *(cts if len(cts) > 1 else cts))
            if isinstance(gin, Tensor):
                gin = (gin,)
            return tuple(
                g._data if isinstance(g, Tensor) else g for g in gin
            )

        node = GradNode(cls.__name__, None, vjp_fn, tensor_inputs, out_tensors)
        # PyLayer graphs can be re-run (backward clears consumed only on release)
        for j, t in enumerate(out_tensors):
            t._grad_node = node
            t._out_index = j
        return out_tensors[0] if single else tuple(out_tensors)


# paddle.autograd.saved_tensors_hooks — minimal compat
@contextlib.contextmanager
def saved_tensors_hooks(pack_hook, unpack_hook):
    yield
