"""Eager autograd tape + backward engine.

TPU-native re-design of the reference's eager autograd
(paddle/fluid/eager/backward.cc `egr::Backward`, GradNodeBase,
GradTensorHolder — SURVEY.md §2.1 "Eager autograd"): instead of ~200k lines of
codegen'd per-op GradNodes, every op records ONE generic node whose vjp
closure comes from `jax.vjp` at call time.  The closure works on concrete
arrays (eager) and on tracers (inside @to_static), so a *single* autograd
implementation serves both the dygraph path and whole-step XLA compilation.

Double grad (create_graph=True) re-derives each node's VJP *through the
dispatcher* as a differentiable function of (primal inputs, cotangents), so
the backward computation itself lands on the tape.
"""

from __future__ import annotations

import weakref

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import core as _core


def _zeros_for(aval):
    shape, dtype = aval
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


class Edge:
    """Autograd edge captured at record time.

    In-place ops rebind a Tensor's payload/identity (dispatch.inplace_rebind),
    so nodes must NOT chase `t._grad_node` at backward time — they follow the
    producer (node, index) frozen when the consuming op recorded.  `tensor`
    stays for leaf accumulation, hooks, and double-grad connectivity.
    """

    __slots__ = ("node", "index", "tensor")

    def __init__(self, tensor):
        self.node = tensor._grad_node
        self.index = tensor._out_index
        self.tensor = tensor


class GradNode:
    """One recorded op on the tape."""

    __slots__ = (
        "name",
        "primal_fn",
        "vjp_fn",
        "input_edges",
        "out_avals",
        "out_refs",
        "consumed",
        "__weakref__",
    )

    def __init__(self, name, primal_fn, vjp_fn, input_tensors, outputs):
        self.name = name
        self.primal_fn = primal_fn
        self.vjp_fn = vjp_fn
        self.input_edges = [Edge(t) for t in input_tensors]
        self.out_avals = [(tuple(o._raw.shape), jnp.dtype(o._raw.dtype)) for o in outputs]
        self.out_refs = [weakref.ref(o) for o in outputs]
        self.consumed = False

    @property
    def n_out(self):
        return len(self.out_avals)

    @property
    def input_tensors(self):
        return [e.tensor for e in self.input_edges]

    def parents(self):
        seen = []
        for e in self.input_edges:
            if e.node is not None and e.node not in seen:
                seen.append(e.node)
        return seen

    def release(self):
        self.vjp_fn = None
        self.primal_fn = None
        self.consumed = True

    # -- apply ----------------------------------------------------------
    def apply_fast(self, cotangents):
        """cotangents: list (len n_out) of raw arrays or None → raw input cts."""
        if self.consumed or self.vjp_fn is None:
            raise RuntimeError(
                f"Trying to run backward through op '{self.name}' a second time. "
                "Set retain_graph=True if you need to backward multiple times."
            )
        cts = tuple(
            c if c is not None else _zeros_for(av)
            for c, av in zip(cotangents, self.out_avals)
        )
        return self.vjp_fn(cts)

    def apply_create_graph(self, cotangents):
        """Record the VJP as tape ops; cotangents are Tensors or None."""
        from ..ops.dispatch import apply as _apply
        from ..tensor import Tensor

        if self.primal_fn is None:
            raise RuntimeError(
                f"Graph for op '{self.name}' was already released; "
                "use retain_graph=True for double backward."
            )
        n_in = len(self.input_tensors)
        live_ct = [(i, c) for i, c in enumerate(cotangents) if c is not None]
        live_idx = [i for i, _ in live_ct]
        avals = self.out_avals
        primal_fn = self.primal_fn

        def bwd(*flat):
            xs = flat[:n_in]
            cts_in = flat[n_in:]
            _, vjp = jax.vjp(primal_fn, *xs)
            full = []
            k = 0
            for j, av in enumerate(avals):
                if j in live_idx:
                    full.append(cts_in[k])
                    k += 1
                else:
                    full.append(_zeros_for(av))
            return vjp(tuple(full))

        ct_tensors = []
        for _, c in live_ct:
            if not isinstance(c, Tensor):
                t = Tensor.__new__(Tensor)
                t._init_from_array(c, stop_gradient=True)
                c = t
            ct_tensors.append(c)
        outs = _apply(bwd, list(self.input_tensors) + ct_tensors,
                      name=f"{self.name}_grad", multi=True)
        return outs  # tuple of Tensors, one per input


def _acc(a, b):
    """Accumulate cotangents; handles None / raw arrays / Tensors."""
    if a is None:
        return b
    if b is None:
        return a
    from ..tensor import Tensor

    if isinstance(a, Tensor) or isinstance(b, Tensor):
        from .. import ops

        if not isinstance(a, Tensor):
            t = Tensor.__new__(Tensor)
            a = t._init_from_array(a, stop_gradient=True)
        if not isinstance(b, Tensor):
            t = Tensor.__new__(Tensor)
            b = t._init_from_array(b, stop_gradient=True)
        return ops.add(a, b)
    if isinstance(a, np.ndarray) and a.dtype == jax.dtypes.float0:
        return a
    return a + b


def _raw(x):
    from ..tensor import Tensor

    return x._data if isinstance(x, Tensor) else x


def _topo_order(roots):
    """Topological order of reachable nodes (parents before children)."""
    order = []
    state = {}  # node -> 0 visiting, 1 done

    for root in roots:
        if root in state:
            continue
        stack = [(root, iter(root.parents()))]
        state[root] = 0
        while stack:
            node, it = stack[-1]
            advanced = False
            for p in it:
                if p not in state:
                    state[p] = 0
                    stack.append((p, iter(p.parents())))
                    advanced = True
                    break
            if not advanced:
                state[node] = 1
                order.append(node)
                stack.pop()
    return order


# callables invoked after every completed backward pass (weakly keyed by
# owner so a dropped DataParallel wrapper unregisters itself) — the dygraph
# Reducer uses this to finalize gradient synchronization without requiring
# an explicit apply_collective_grads() call (reference: reducer.cc syncs
# during backward automatically)
_post_backward_hooks = weakref.WeakKeyDictionary()

# callables invoked BEFORE the backward traversal with the set of reachable
# leaf-tensor ids — the Reducer uses this to pre-mark params unreachable
# from the loss so its in-order bucket flush keeps overlapping under
# find_unused_parameters (reference: reducer.cc prepare_for_backward's
# graph walk)
_pre_backward_hooks = weakref.WeakKeyDictionary()


def register_pre_backward_hook(owner, fn):
    import inspect

    if inspect.ismethod(fn):
        _pre_backward_hooks[owner] = weakref.WeakMethod(fn)
    else:
        _pre_backward_hooks[owner] = fn


def register_post_backward_hook(owner, fn):
    # a bound method of `owner` stored as the VALUE would strongly reference
    # the key and pin the entry forever (the WeakKeyDictionary caveat) —
    # store it as a WeakMethod and resolve at call time instead
    import inspect

    if inspect.ismethod(fn):
        _post_backward_hooks[owner] = weakref.WeakMethod(fn)
    else:
        _post_backward_hooks[owner] = fn


def run_backward(
    outputs,
    out_grads=None,
    inputs=None,
    accumulate_into_leaves=True,
    create_graph=False,
    retain_graph=False,
):
    """Shared engine for Tensor.backward and paddle.grad.

    Returns dict id(tensor) -> cotangent for requested `inputs` (if given).
    """
    from ..tensor import Tensor

    retain_graph = retain_graph or create_graph

    if out_grads is None:
        out_grads = [None] * len(outputs)

    requested = {id(t): None for t in (inputs or [])}
    requested_tensors = {id(t): t for t in (inputs or [])}

    node_cts = {}
    roots = []
    leaf_results = []  # (tensor, grad) pairs resolved pre-topo (direct leaves)

    for t, g in zip(outputs, out_grads):
        if g is None:
            if not jnp.issubdtype(jnp.dtype(t._raw.dtype), jnp.inexact):
                raise RuntimeError("backward() on non-float tensor requires grad_tensor")
            g = jnp.ones(t._raw.shape, t._raw.dtype)
        else:
            g = _raw(g) if not create_graph else (g if isinstance(g, Tensor) else g)
        node = t._grad_node
        if node is None:
            if not t.stop_gradient:
                leaf_results.append((t, g))
            continue
        slots = node_cts.setdefault(node, [None] * node.n_out)
        slots[t._out_index] = _acc(slots[t._out_index], g)
        roots.append(node)

    order = _topo_order(roots)

    if _pre_backward_hooks:
        reachable = {id(t) for t, _ in leaf_results}
        for node in order:
            for e in node.input_edges:
                if e.node is None and e.tensor is not None:
                    reachable.add(id(e.tensor))
        for cb in list(_pre_backward_hooks.values()):
            if isinstance(cb, weakref.WeakMethod):
                cb = cb()
                if cb is None:
                    continue
            cb(reachable)

    def _apply_hooks(t, g):
        if t is not None and t._hooks:
            for h in t._hooks:
                r = h(_wrap(g))
                if r is not None:
                    g = r._data if isinstance(r, Tensor) else r
        return g

    def _wrap(g):
        if isinstance(g, Tensor):
            return g
        t = Tensor.__new__(Tensor)
        return t._init_from_array(g, stop_gradient=not create_graph)

    def _route_leaf(t, g):
        g = _apply_hooks(t, g)
        if id(t) in requested:
            requested[id(t)] = _acc(requested[id(t)], g)
        if accumulate_into_leaves and not t.stop_gradient:
            newg = _acc(t.grad, g)
            t.grad = newg

    for t, g in leaf_results:
        _route_leaf(t, g)

    for node in reversed(order):
        cts = node_cts.pop(node, None)
        if cts is None:
            continue
        # output hooks + requested intermediates
        for j, ref in enumerate(node.out_refs):
            ot = ref()
            if ot is None:
                continue
            if cts[j] is not None:
                cts[j] = _apply_hooks(ot, cts[j])
                if id(ot) in requested:
                    requested[id(ot)] = _acc(requested[id(ot)], cts[j])
        if create_graph:
            in_cts = node.apply_create_graph([c if c is None else _wrap(c) for c in cts])
        else:
            in_cts = node.apply_fast([_raw(c) if c is not None else None for c in cts])
        for e, g in zip(node.input_edges, in_cts):
            if g is None:
                continue
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            if e.node is not None:
                slots = node_cts.setdefault(e.node, [None] * e.node.n_out)
                slots[e.index] = _acc(slots[e.index], g)
            else:
                _route_leaf(e.tensor, g)
        if not retain_graph:
            node.release()

    out = {}
    for tid, g in requested.items():
        t = requested_tensors[tid]
        if g is None:
            out[tid] = None
        else:
            out[tid] = _wrap(g) if not isinstance(g, Tensor) else g
            if not create_graph:
                out[tid].stop_gradient = True
    for cb in list(_post_backward_hooks.values()):
        if isinstance(cb, weakref.WeakMethod):
            cb = cb()
            if cb is None:
                continue
        cb()
    return out
