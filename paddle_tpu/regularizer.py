"""paddle.regularizer — L1Decay / L2Decay (reference:
python/paddle/regularizer.py).  Optimizers accept these wherever a float
`weight_decay` goes; `coeff` carries the strength."""

from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class _Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __float__(self):
        return self.coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self.coeff})"


class L2Decay(_Decay):
    """Classic weight decay: grad += coeff * param."""


class L1Decay(_Decay):
    """L1 regularization: grad += coeff * sign(param)."""
