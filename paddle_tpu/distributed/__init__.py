"""paddle_tpu.distributed (reference surface: python/paddle/distributed/)."""

from .env import (  # noqa: F401
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
)
from .collective import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    broadcast,
    broadcast_object_list,
    get_group,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    stream,
)
from . import mesh  # noqa: F401
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from . import checkpoint  # noqa: F401
from .auto_parallel import (  # noqa: F401
    DistAttr,
    Partial,
    ProcessMesh,
    Replicate,
    Shard,
    dtensor_from_fn,
    reshard,
    shard_layer,
    shard_optimizer,
    shard_tensor,
)
from .fleet.meta_parallel.parallel_wrappers import DataParallel  # noqa: F401


def TCPStore(*args, **kwargs):
    """Native rendezvous store (reference: paddle.distributed TCPStore)."""
    from ..native import TCPStore as _TCPStore

    return _TCPStore(*args, **kwargs)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Single-controller JAX sees all local chips in one process; spawn runs
    func once (the reference forks one process per GPU)."""
    func(*args)
    return None


def launch():
    from .launch.main import main

    main()
