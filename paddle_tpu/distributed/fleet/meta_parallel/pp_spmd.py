"""SPMD collective pipelining over the 'pp' mesh axis (reference:
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py +
pp_utils/p2p_communication.py: stage-resident weights, NCCL p2p activation
transfer — SURVEY.md §2.2 "PP", §7 M6).

TPU-native re-design, NOT a port of the reference's per-rank runtime:

- Stage weights are STACKED on a leading layer dim and sharded
  ``P('pp')`` — each pp coordinate holds only its own stages' parameters,
  so per-device parameter bytes shrink ~1/pp (the reference reaches the
  same via per-rank construction; here it is one sharded array).
- The microbatch schedule is a ``lax.scan`` over pipeline ticks INSIDE a
  partial-manual ``shard_map`` over the 'pp' axis: at each tick every
  stage applies its layer chunk to the activation it holds, then hands it
  to the next stage with ``lax.ppermute`` (the ICI p2p the reference does
  with batched NCCL isend/irecv).
- The whole pipeline is one differentiable function: ``jax.vjp`` reverses
  the scan and the ppermute, so the backward pass is the mirrored
  pipeline (cotangents flow stage->stage over ICI).  Microbatching and
  gradient accumulation live inside the program — a train step is just
  loss.backward(); opt.step() on the mean-over-microbatches loss.
- dp / mp / sharding remain AUTO axes: batch stays dp-sharded and
  Megatron-TP sharding constraints keep working inside each stage, so
  DP x TP x PP composes in one compiled program.

Memory follows GPipe-with-remat, bounded by one activation per in-flight
microbatch per stage (``remat=True`` recomputes block internals in the
backward).  The 1F1B emission-order scheduler in pipeline_parallel.py
remains the eager/debug path; this is the on-mesh execution path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ... import mesh as _mesh

_AXIS = "pp"


def stage_scan(block_fn, local_params, h, remat=True):
    """Apply this stage's layer chunk: scan block_fn over the leading
    (local-layer) dim of every leaf in `local_params`."""
    body = jax.checkpoint(block_fn) if remat else block_fn

    def step(carry, layer_params):
        return body(layer_params, carry), None

    h, _ = jax.lax.scan(step, h, local_params)
    return h


def virtual_layer_order(n_layers, pp, num_virtual):
    """Physical storage order for interleaved virtual stages: position
    (s, vi, j) holds LOGICAL layer (vi*pp + s)*l + j, so a plain contiguous
    P('pp') dim-0 sharding gives stage s exactly its `num_virtual` chunks
    (Megatron placement: chunk c runs on stage c % pp).  Returns the
    logical-layer index for each physical slot; identity when v == 1."""
    l = n_layers // (pp * num_virtual)
    order = []
    for s in range(pp):
        for vi in range(num_virtual):
            for j in range(l):
                order.append((vi * pp + s) * l + j)
    return order


def pipeline_apply(block_fn, stacked_params, x, n_micro, axis_name=_AXIS,
                   mesh=None, remat=True, num_virtual=1):
    """Run `x` through all stacked layers with pp-pipelined execution.

    block_fn(layer_params, h) -> h applies ONE layer (leaf shapes without
    the leading layer dim).  `stacked_params` is a pytree whose leaves
    have leading dim = total layer count, sharded P('pp') on dim 0 — for
    num_virtual > 1 the layers must be STORED in virtual_layer_order().
    x: [B, S, H] hidden states with B % n_micro == 0.  Returns [B, S, H].

    num_virtual > 1 accepts Megatron-interleaved WEIGHT PLACEMENT (chunk c
    on stage c % pp, stored in virtual_layer_order) and executes the chunk
    columns as sequential pipeline passes — each column pipelines normally
    and the activation wraps the ring back to stage 0 between columns.
    This keeps AD memory at one activation per in-flight microbatch; the
    true circular schedule (which also shrinks the bubble by v) needs
    per-stage wait buffers whose scan carries multiply activation memory
    by n_micro — rejected for now, documented honestly.

    pp == 1 (or no mesh) degenerates to a plain scan over layers.
    """
    mesh = mesh or _mesh.get_mesh()
    pp = 1 if mesh is None or axis_name not in mesh.axis_names else mesh.shape[axis_name]
    if pp <= 1:
        return stage_scan(block_fn, stacked_params, x, remat)

    n_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_layers % (pp * num_virtual) != 0:
        raise ValueError(
            f"pipeline needs layer count ({n_layers}) divisible by "
            f"pp degree * num_virtual ({pp} * {num_virtual})"
        )
    b = x.shape[0]
    if b % n_micro != 0:
        raise ValueError(f"batch {b} not divisible by num microbatches {n_micro}")
    mb = b // n_micro
    # microbatch-major view; pin the per-microbatch batch dim to 'dp' so every
    # tick uses the full dp width (the reshape alone would leave microbatches
    # stacked inside single dp shards)
    xs0 = x.reshape((n_micro, mb) + x.shape[1:])
    xs0 = _mesh.constraint(xs0, P(None, "dp"))

    def local_fn(params, xs):
        idx = jax.lax.axis_index(axis_name)
        is_first = idx == 0
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        pad = jnp.zeros((pp - 1,) + xs.shape[1:], xs.dtype)
        xs_pad = jnp.concatenate([xs, pad], axis=0)  # [ticks, mb, S, H]

        def tick(h_prev, x_t):
            # stage 0 injects a fresh microbatch; stages s>0 consume the
            # activation their neighbor pushed last tick
            h_in = jnp.where(is_first, x_t, h_prev)
            h_out = stage_scan(block_fn, params, h_in, remat)
            h_next = jax.lax.ppermute(h_out, axis_name, perm)
            return h_next, h_out

        _, hs = jax.lax.scan(tick, jnp.zeros_like(xs[0]), xs_pad)
        # ticks pp-1 .. ticks-1 of the LAST stage are the pipeline outputs;
        # other stages return garbage that the caller's slice discards
        return hs[pp - 1 :]

    param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)

    def run_column(params, xs):
        fn = jax.shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=P(axis_name),
            axis_names={axis_name},
            check_vma=False,
        )
        stacked_out = fn(params, xs)  # [pp * n_micro, mb, S, H]
        out = stacked_out.reshape((pp, n_micro, mb) + x.shape[1:])[-1]
        return _mesh.constraint(out, P(None, "dp"))

    if num_virtual == 1:
        out = run_column(stacked_params, xs0)
        return out.reshape(x.shape)

    # interleaved storage: local leaves are [v*l, ...] in (vi, j) order; a
    # global reshape + slice gives chunk column vi still P('pp')-sharded
    xs = xs0
    lpc = n_layers // (pp * num_virtual)  # layers per chunk
    for vi in range(num_virtual):
        col = jax.tree_util.tree_map(
            lambda a: a.reshape((pp, num_virtual, lpc) + a.shape[1:])[:, vi]
            .reshape((pp * lpc,) + a.shape[1:]),
            stacked_params,
        )
        col = jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(
                a, jax.sharding.NamedSharding(mesh, P(axis_name))
            ),
            col,
        )
        xs = run_column(col, xs)
    return xs.reshape(x.shape)


def place_stacked_param(t, extra_spec=()):
    """Put a stacked parameter Tensor on its pp shards (dim 0), optionally
    sharding further dims (e.g. ('mp',) columns for TP composition)."""
    spec = P(_AXIS, *extra_spec)
    return _mesh.shard_tensor_(t, spec)


def pp_world_size(mesh=None):
    mesh = mesh or _mesh.get_mesh()
    if mesh is None or _AXIS not in mesh.axis_names:
        return 1
    return mesh.shape[_AXIS]


__all__ = [
    "pipeline_apply",
    "stage_scan",
    "place_stacked_param",
    "pp_world_size",
]
