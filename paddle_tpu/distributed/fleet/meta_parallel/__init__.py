from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from .random_ctrl import (  # noqa: F401
    RNGStatesTracker,
    get_rng_state_tracker,
    model_parallel_random_seed,
)
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .parallel_wrappers import DataParallel, TensorParallel, ShardingParallel  # noqa: F401
from . import sp_utils  # noqa: F401
