"""Eager-mode bucketed gradient synchronization for dygraph DataParallel
(reference: paddle/fluid/distributed/collective/reducer.cc — SURVEY.md §2.2
"Reducer (DP)").

The reference overlaps NCCL allreduce with backward by hooking gradient
accumulation and flushing fixed-size buckets.  Here the same structure runs
over the single-controller encoding: parameters are bucketed in reverse
construction order (gradients arrive roughly reverse-forward), a grad hook
marks readiness with an O(1) per-bucket counter, and buckets flush in
order as soon as a LATER bucket starts receiving gradients (by which point
their members' contributions are fully accumulated) — one fused
(concat-flat) all_reduce AVG per bucket, dispatched asynchronously so the
exchange overlaps the remainder of backward.

Multiply-used parameters may receive further contributions after their
bucket flushed; such buckets are marked dirty and re-reduced in
finalize() — AVG is linear, so re-averaging (already-averaged + new local
contribution) yields exactly the global average.

The hooks are inert inside @to_static traced backward (tracer grads):
compiled steps get their gradient reduction from GSPMD inside the program.
"""

from __future__ import annotations

import numpy as np
import jax

from ....framework import core as _core
from ....tensor import Tensor
from ... import collective as _collective


# (shape, dtype) -> (mesh, jitted mean, sharding) — one compiled executable
# per bucket geometry, reused every step
_XPROC_CACHE = {}


def _cross_process_mean(arr):
    """Average a process-local flat bucket across all processes — one
    contribution PER PROCESS, regardless of how many devices each holds.

    The local bucket is placed on each local device as one [1, n] shard of
    a global [n_devices, n] array over a 1-axis mesh; a cached compiled
    `sum(axis=0)` (replicated output) runs as one SPMD program — XLA
    lowers it to an all-reduce, and no host ever holds a stacked
    [world, n] array.  Each local shard is pre-scaled by
    1 / (process_count * local_device_count): a process contributes
    exactly arr / process_count however many devices it has, so the
    result is the true per-process mean even on heterogeneous topologies
    (a plain mean over the device axis would silently weight each process
    by its local device count).  Every process must flush buckets in the
    same order (they do: bucket assignment is deterministic), the usual
    collective contract."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    import jax.numpy as jnp

    key = (tuple(arr.shape), str(arr.dtype))
    ent = _XPROC_CACHE.get(key)
    if ent is None:
        devs = np.asarray(jax.devices())  # all devices, every process
        mesh = Mesh(devs, ("d",))
        in_s = NamedSharding(mesh, P("d"))
        out_s = NamedSharding(mesh, P())
        out_dtype = jnp.dtype(arr.dtype)

        fn = jax.jit(
            lambda a: a.sum(0).astype(out_dtype),
            in_shardings=in_s,
            out_shardings=out_s,
        )
        ent = (mesh, in_s, fn)
        _XPROC_CACHE[key] = ent
    mesh, in_s, fn = ent
    scale = 1.0 / (jax.process_count() * len(jax.local_devices()))
    local = arr.astype(jnp.float32) * scale
    shards = [jax.device_put(local[None], d) for d in jax.local_devices()]
    garr = jax.make_array_from_single_device_arrays(
        (len(mesh.devices.ravel()),) + tuple(arr.shape), in_s, shards
    )
    out = fn(garr)
    # replicated result: hand back this process's addressable copy
    return out.addressable_data(0)


class Reducer:
    def __init__(self, parameters, group=None, bucket_cap_mb=25, find_unused_parameters=False):
        self._params = [p for p in parameters if not p.stop_gradient]
        self._group = group
        self._find_unused = find_unused_parameters
        self._enabled = True

        # bucket assignment: reverse order, capped by bytes
        cap = int(bucket_cap_mb * 1024 * 1024)
        self._buckets = []
        # buckets are homogeneous in dtype (reference reducer groups per
        # dtype): the fused flush concats grads, and a mixed bucket would
        # silently promote every member to the widest dtype
        cur, cur_bytes, cur_dtype = [], 0, None
        for p in reversed(self._params):
            nbytes = int(np.prod(p.shape or [1])) * p.element_size()
            if cur and p.dtype != cur_dtype:
                self._buckets.append(cur)
                cur, cur_bytes = [], 0
            cur_dtype = p.dtype
            cur.append(p)
            cur_bytes += nbytes
            if cur_bytes >= cap:
                self._buckets.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            self._buckets.append(cur)
        self._bucket_of = {}
        for bi, b in enumerate(self._buckets):
            for p in b:
                self._bucket_of[id(p)] = bi
        # single-controller short circuit: with one process, eager grads from
        # a global-batch loss are already globally reduced (GSPMD semantics),
        # so the AVG allreduce is the identity — skip the bucket machinery on
        # the hot path.  Tests set _force_sync to exercise it anyway; real
        # multi-process deployments take it unconditionally.
        self._force_sync = False
        self._reset_state()
        import weakref

        wr = weakref.ref(self)
        for p in self._params:
            p.register_hook(self._weak_hook(wr, id(p)))
        # finalize automatically at the end of every backward pass (the
        # reference Reducer syncs during backward with no explicit call)
        from ....autograd.engine import (
            register_post_backward_hook,
            register_pre_backward_hook,
        )

        register_post_backward_hook(self, self._on_backward_done)
        if self._find_unused:
            # reference reducer.cc prepare_for_backward: walk the graph up
            # front to mark params unreachable from the loss, so the
            # in-order flush below never stalls waiting for them — overlap
            # stays on under find_unused_parameters
            register_pre_backward_hook(self, self._on_backward_start)

    @staticmethod
    def _weak_hook(wr, pid):
        """Grad hook holding the Reducer WEAKLY: params outlive the
        DataParallel wrapper, so a strong closure would keep every Reducer
        ever constructed alive (and stack their syncs on re-wrap)."""

        def hook(grad):
            self = wr()
            if self is None:
                return grad
            return self._hook_impl(pid, grad)

        return hook

    def _sync_needed(self):
        import jax

        return self._force_sync or jax.process_count() > 1

    def _on_backward_start(self, reachable_ids):
        """Pre-mark params the loss cannot reach as ready (no grad will
        arrive for them this backward)."""
        if not (self._enabled and self._sync_needed()):
            return
        if _core.active_trace() is not None:
            return
        for p in self._params:
            pid = id(p)
            if pid not in reachable_ids and pid not in self._ready:
                bi = self._bucket_of.get(pid)
                if bi is not None:
                    self._ready.add(pid)
                    self._remaining[bi] -= 1

    def _on_backward_done(self):
        if _core.active_trace() is not None:
            # a compiled step's backward fired the hook: GSPMD reduces
            # gradients inside the program — eager flushing here would
            # record stray ops (and write tracers into grads) of whatever
            # params this Reducer still tracks
            return
        if self._enabled and self._sync_needed():
            self.finalize()
        else:
            self._reset_state()

    def _reset_state(self):
        self._ready = set()
        self._remaining = [len(b) for b in self._buckets]
        self._synced = [False] * len(self._buckets)
        self._next_unflushed = 0

    def _hook_impl(self, pid, grad):
        raw = grad._data if isinstance(grad, Tensor) else grad
        if (
            not self._enabled
            or not self._sync_needed()
            or _core.active_trace() is not None
            or isinstance(raw, jax.core.Tracer)
        ):
            return grad  # compiled steps: GSPMD reduces inside the program
        bi = self._bucket_of.get(pid)
        if bi is None:
            return grad
        if pid not in self._ready:
            self._ready.add(pid)
            self._remaining[bi] -= 1
        elif self._synced[bi]:
            # extra contribution after the bucket already flushed
            # (multiply-used parameter): needs a re-reduce at finalize
            self._synced[bi] = False
        # in-order overlap flush: buckets strictly BEFORE this one have
        # fully-accumulated grads once a later bucket starts arriving
        # (under find_unused_parameters the pre-backward graph walk already
        # marked unreachable params ready, so the order never stalls)
        while (
            self._next_unflushed < bi
            and self._remaining[self._next_unflushed] == 0
        ):
            j = self._next_unflushed
            if not self._synced[j]:
                self._flush(self._buckets[j])
                self._synced[j] = True
            self._next_unflushed += 1
        return grad

    def _flush(self, bucket):
        if jax.process_count() > 1:
            # rank-invariant geometry: with find_unused_parameters and
            # data-dependent branches, ranks may disagree on WHICH params
            # have grads — the fused collective must still line up, so
            # absent grads ride as zeros and every bucket member gets the
            # cross-rank average written back (torch DDP semantics)
            from ....ops.creation import zeros_like as _zeros_like

            pairs = [
                (p, p.grad if p._grad_raw is not None else _zeros_like(p))
                for p in bucket
            ]
        else:
            pairs = [(p, p.grad) for p in bucket if p._grad_raw is not None]
        if not pairs:
            return
        if not self._force_sync:
            raw = pairs[0][0]._grad_raw
            if isinstance(raw, jax.Array) and not raw.is_fully_addressable:
                # multi-host GLOBAL array: the gradient is already globally
                # consistent by construction (loss spans the global
                # dp-sharded batch) — an extra allreduce is both redundant
                # and unrunnable eagerly on non-addressable shards.  The
                # bucket path is for process-LOCAL gradient arrays.
                return
        import jax.numpy as jnp

        from ....ops.manipulation import concat, reshape, split

        grads = [g for _, g in pairs]
        flat = concat([reshape(g, [-1]) for g in grads], axis=0)
        if jax.process_count() > 1:
            # process-local grads on a multi-process job: the fused bucket
            # becomes ONE shard of a global array and a cached compiled
            # mean-reduce runs SPMD over all processes — O(bucket) memory
            # per host, a real allreduce on the wire (reference reducer.cc
            # fused allreduce; SURVEY §5.8 eager-collectives design).  The
            # old process_allgather+mean materialized [world, bucket] on
            # every host.
            flat._data = _cross_process_mean(flat._raw)
        else:
            _collective.all_reduce(flat, op=_collective.ReduceOp.AVG, group=self._group)
        sizes = [int(np.prod(g.shape or [1])) for g in grads]
        pieces = split(flat, sizes, axis=0)
        for (p, g), piece in zip(pairs, pieces):
            p._grad_raw = reshape(piece, list(g.shape))._raw

    def finalize(self):
        """Synchronize every bucket not already flushed — or flushed but
        dirtied by a post-flush contribution (reference:
        Reducer::FinalizeBackward); called from apply_collective_grads."""
        for bi, bucket in enumerate(self._buckets):
            if not self._synced[bi]:
                self._flush(bucket)
        self._reset_state()

    def set_enabled(self, flag):
        self._enabled = bool(flag)
