"""Pipeline-parallel layers (reference:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py:
LayerDesc / SharedLayerDesc / PipelineLayer — SURVEY.md §2.2 "PP").

The stage partition (LayerDesc list → segments) is preserved; microbatched
execution with gradient accumulation runs inside ONE program with weights
replicated across devices (scheduler path — see pipeline_parallel.py).
For stage weights physically sharded over the 'pp' axis with ppermute
p2p, use the homogeneous stacked-weight path (pp_spmd.pipeline_apply /
models.gpt.GPTForCausalLMSpmdPipe).
"""

from __future__ import annotations

import math

from ....nn.layer import Layer
from ....nn.container import LayerList
from ..topology import get_hybrid_communicate_group


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(
        self,
        layers,
        num_stages=None,
        topology=None,
        loss_fn=None,
        seg_method="uniform",
        recompute_interval=0,
        recompute_ctx=None,
        num_virtual_pipeline_stages=None,
    ):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        hcg = get_hybrid_communicate_group()
        self._num_stages = num_stages or hcg.get_pipe_parallel_world_size()
        self._num_virtual = num_virtual_pipeline_stages or 1
        self._recompute_interval = recompute_interval

        self._layers_desc = list(layers)
        self._shared_layers = {}
        built = []
        for d in self._layers_desc:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared_layers:
                    self._shared_layers[d.layer_name] = d.build_layer()
                built.append((self._shared_layers[d.layer_name], d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, None))
            else:
                raise TypeError(f"Unsupported pipeline layer desc {d!r}")
        self.run_function = built
        self._sublist = LayerList([l for l, _ in built if isinstance(l, Layer)])

        # chunk segmentation: num_stages * num_virtual chunks; with
        # num_virtual > 1, chunk c runs on physical stage c % num_stages
        # (Megatron interleaved placement — reference pp_layers.py
        # _construct_shared_comm / get_stage_from_index)
        n = len(built)
        total = self._num_stages * self._num_virtual
        per = max(1, math.ceil(n / total))
        self._segments = [
            (i * per, min((i + 1) * per, n)) for i in range(total)
        ]

    @property
    def num_chunks(self):
        """Virtual-stage chain length (== num_stages when not interleaved)."""
        return len(self._segments)

    def chunk_functions(self, chunk):
        lo, hi = self._segments[chunk]
        return self.run_function[lo:hi]

    def get_stage_from_index(self, index):
        for cid, (lo, hi) in enumerate(self._segments):
            if lo <= index < hi:
                return cid % self._num_stages
        return self._num_stages - 1

    def forward(self, x):
        for layer, fwd in self.run_function:
            if fwd is not None:
                x = fwd(layer, x)
            elif isinstance(layer, Layer) or callable(layer):
                x = layer(x)
        return x

    def loss(self, output, label):
        if self._loss_fn is None:
            raise RuntimeError("PipelineLayer built without loss_fn")
        return self._loss_fn(output, label)
