"""Model wrappers for each parallelism (reference:
python/paddle/distributed/fleet/meta_parallel/{data_parallel,*}.py +
paddle.DataParallel in python/paddle/fluid/dygraph/parallel.py).

TPU-native DP: inputs arrive batch-sharded over the 'dp' mesh axis
(DistributedBatchSampler → device_put with P('dp', ...)); gradients come out
correctly reduced because the loss reduction spans the global batch under
GSPMD — no Reducer/bucketing machinery is needed (the reference's
reducer.cc exists to overlap NCCL with backward; XLA's latency-hiding
scheduler owns that here)."""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from ....nn.layer import Layer
from ....ops.dispatch import apply, coerce
from ....tensor import Tensor
from ... import mesh as _mesh


class _Wrapper(Layer):
    def __init__(self, layers):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    @property
    def parameters_(self):
        return self._layers.parameters()


class DataParallel(_Wrapper):
    """paddle.DataParallel — shards incoming batches over the 'dp' axis."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__(layers)
        if _mesh.get_mesh() is None and len(jax.devices()) > 1:
            _mesh.build_mesh(dp=-1)

    def _shard_input(self, t):
        if not isinstance(t, Tensor) or _mesh.get_mesh() is None:
            return t
        nd = len(t.shape)
        spec = P("dp", *([None] * (nd - 1)))
        sh = _mesh.sharding_for(spec)
        if sh is not None and not isinstance(t._raw, jax.core.Tracer):
            t = Tensor(jax.device_put(t._raw, sh), stop_gradient=t.stop_gradient)
        return t

    def forward(self, *args, **kwargs):
        args = tuple(self._shard_input(a) for a in args)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    @staticmethod
    def no_sync():
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            yield

        return _ctx()


class TensorParallel(_Wrapper):
    """Weights already carry 'mp' shardings from the mp layers."""


class ShardingParallel(_Wrapper):
    pass


class SegmentParallel(_Wrapper):
    pass
