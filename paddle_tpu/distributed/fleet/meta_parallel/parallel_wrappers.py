"""Model wrappers for each parallelism (reference:
python/paddle/distributed/fleet/meta_parallel/{data_parallel,*}.py +
paddle.DataParallel in python/paddle/fluid/dygraph/parallel.py).

TPU-native DP: inputs arrive batch-sharded over the 'dp' mesh axis
(DistributedBatchSampler → device_put with P('dp', ...)).  In the
single-controller execution model, gradients come out correctly reduced
because the loss reduction spans the global batch under GSPMD, so the
dygraph Reducer (reducer.py — bucketed allreduce with backward-hook
overlap, the reference reducer.cc contract) short-circuits; on
multi-process deployments, where per-rank grads genuinely differ outside
compiled steps, it runs unconditionally and finalizes automatically at the
end of each backward."""

from __future__ import annotations

import jax
from jax.sharding import PartitionSpec as P

from ....nn.layer import Layer
from ....ops.dispatch import apply, coerce
from ....tensor import Tensor
from ... import mesh as _mesh


def dp_sharding(ndim):
    """NamedSharding that splits dim 0 over the 'dp' mesh axis (the input
    placement DataParallel gives incoming batches); None when no mesh is
    up, the mesh has no dp axis, or the value is 0-d."""
    m = _mesh.get_mesh()
    if m is None or "dp" not in m.axis_names or ndim == 0:
        return None
    return _mesh.sharding_for(P("dp", *([None] * (ndim - 1))))


def dp_device_put(raw):
    """H2D-place one host batch array with the dp input placement — the
    shared primitive behind DataParallel._shard_input and the DataLoader's
    prefetch_to_device stage, so prefetched batches land on device already
    sharded the way the wrapped forward expects them.  Falls back to an
    unsharded (uncommitted) device_put when the batch dim doesn't tile the
    dp axis or no mesh is configured."""
    sh = dp_sharding(getattr(raw, "ndim", 0))
    shape = getattr(raw, "shape", ())
    if sh is None or shape[0] % _mesh.axis_size("dp"):
        return jax.device_put(raw)
    if jax.process_count() > 1:
        # multi-host: this process holds its LOCAL batch; assemble the
        # global dp-sharded array (batch dim grows to local * processes)
        import numpy as np

        return jax.make_array_from_process_local_data(sh, np.asarray(raw))
    return jax.device_put(raw, sh)


class _Wrapper(Layer):
    def __init__(self, layers):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    @property
    def parameters_(self):
        return self._layers.parameters()


class DataParallel(_Wrapper):
    """paddle.DataParallel — shards incoming batches over the 'dp' axis."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__(layers)
        if _mesh.get_mesh() is None and len(jax.devices()) > 1:
            _mesh.build_mesh(dp=-1)
        from .reducer import Reducer

        # eager (dygraph) gradient sync path: bucketed allreduce with
        # backward-hook overlap (reference collective/reducer.cc); compiled
        # steps never reach it — GSPMD reduces grads inside the program
        self._reducer = Reducer(
            list(layers.parameters()),
            group=group,
            bucket_cap_mb=comm_buffer_size,
            find_unused_parameters=find_unused_parameters,
        )

    def _shard_input(self, t):
        if not isinstance(t, Tensor) or _mesh.get_mesh() is None:
            return t
        sh = dp_sharding(len(t.shape))
        raw = t._raw
        if sh is None or isinstance(raw, jax.core.Tracer):
            return t
        if isinstance(raw, jax.Array) and (
            not raw.is_fully_addressable or raw.sharding == sh
        ):
            # already a global (or correctly sharded) array — e.g. the
            # output of a previous wrapped forward; re-assembling it would
            # crash or double-concatenate the batch
            return t
        if jax.process_count() > 1:
            # multi-host: each process feeds its LOCAL batch (the reference's
            # per-rank DataLoader contract); assemble the global dp-sharded
            # array from the per-process shards — batch dim grows to
            # local * num_processes.  Inputs are host-resident by contract
            # (DataLoader numpy); a stray device array pays one host hop.
            import numpy as np

            arr = jax.make_array_from_process_local_data(sh, np.asarray(raw))
            return Tensor(arr, stop_gradient=t.stop_gradient)
        return Tensor(jax.device_put(raw, sh), stop_gradient=t.stop_gradient)

    def forward(self, *args, **kwargs):
        args = tuple(self._shard_input(a) for a in args)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        self._reducer._on_backward_done()

    def no_sync(self):
        import contextlib

        reducer = self._reducer

        @contextlib.contextmanager
        def _ctx():
            prev = reducer._enabled
            reducer.set_enabled(False)
            try:
                yield
            finally:
                reducer.set_enabled(prev)  # reentrancy-safe restore

        return _ctx()


class TensorParallel(_Wrapper):
    """Weights already carry 'mp' shardings from the mp layers."""


class ShardingParallel(_Wrapper):
    pass


class SegmentParallel(_Wrapper):
    pass
